"""Serving-runtime tests (ISSUE 11): batched-vs-single bitwise parity,
ragged packing exactness, executable-cache zero-retrace steady state,
tuned-table resolution precedence, router accuracy-class dispatch, and
the stationary-operator caches (condest memo, Ozaki presplit).

Budget notes: single-chip parts use n <= 64; the mesh parts reuse the
8-device mesh at n = 64..96, nb = 8 (shapes other suites already
compile), and nothing calls jax.clear_caches.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.parallel.mesh import make_mesh
from slate_tpu.serve import metrics as serve_metrics
from slate_tpu.serve.batch import (
    gesv_batched,
    pack_block_diag,
    posv_batched,
    unpack_block_diag,
)
from slate_tpu.serve.cache import ExecutableCache, make_key
from slate_tpu.serve.table import (
    TUNED_SCHEMA,
    TUNED_VERSION,
    resolve_request_options,
    use_tuned_table,
)
from slate_tpu.types import Option, SlateError

from conftest import cpu_devices


def mesh24():
    return make_mesh(2, 4, devices=cpu_devices(8))


def _spd_stack(rng, B, n):
    g = rng.standard_normal((B, n, n))
    return jnp.asarray(np.einsum("bij,bkj->bik", g, g) / n
                       + 2 * np.eye(n)[None])


# ---------------------------------------------------------------------------
# batched drivers: bitwise per problem
# ---------------------------------------------------------------------------


def test_batched_bitwise_vs_single(rng):
    from slate_tpu.linalg.chol import posv_array
    from slate_tpu.linalg.lu import gesv_array

    B, n, nrhs = 3, 48, 2
    spd = _spd_stack(rng, B, n)
    b = jnp.asarray(rng.standard_normal((B, n, nrhs)))
    xs, info = posv_batched(spd, b)
    assert np.all(np.asarray(info) == 0)
    for i in range(B):
        ref = posv_array(spd[i], b[i])[0]
        np.testing.assert_array_equal(np.asarray(xs[i]), np.asarray(ref))

    ga = jnp.asarray(rng.standard_normal((B, n, n)) + n * np.eye(n)[None])
    xg, infog = gesv_batched(ga, b)
    assert np.all(np.asarray(infog) == 0)
    for i in range(B):
        ref = gesv_array(ga[i], b[i])[0]
        np.testing.assert_array_equal(np.asarray(xg[i]), np.asarray(ref))


# ---------------------------------------------------------------------------
# ragged block-diagonal packing: pack -> solve -> unpack exact
# ---------------------------------------------------------------------------


def test_pack_roundtrip_exact(rng):
    """Each unpacked solution is BITWISE the solution of the same
    problem packed alone (co-packed operands contribute only structural
    zeros), and matches the unpadded per-problem solve to accuracy."""
    from slate_tpu.linalg.chol import posv_array

    m, sizes, nrhs = 64, [20, 33, 64], 2
    k = len(sizes)
    ops_ = [np.asarray(_spd_stack(rng, 1, s)[0]) for s in sizes]
    rhs_ = [rng.standard_normal((s, nrhs)) for s in sizes]
    a_pack, b_pack = pack_block_diag([jnp.asarray(o) for o in ops_], m,
                                     [jnp.asarray(r) for r in rhs_])
    x_pack, _f, info = posv_array(a_pack, b_pack)
    assert int(info) == 0
    got = unpack_block_diag(x_pack, sizes, m, [nrhs] * k)
    for i, s in enumerate(sizes):
        solo_a, solo_b = pack_block_diag(
            [jnp.asarray(ops_[j]) if j == i else jnp.eye(m, dtype=jnp.float64)
             for j in range(k)], m,
            [jnp.asarray(rhs_[j]) if j == i
             else jnp.zeros((m, nrhs), jnp.float64) for j in range(k)])
        ref = unpack_block_diag(posv_array(solo_a, solo_b)[0], sizes, m,
                                [nrhs] * k)[i]
        np.testing.assert_array_equal(np.asarray(got[i]), np.asarray(ref))
        lone = np.linalg.solve(ops_[i], rhs_[i])
        assert np.abs(np.asarray(got[i]) - lone).max() < 1e-10


def test_posv_packed_mesh_consumes_tuned_table(rng):
    """The packed mesh solve IS a serving request path: unset schedule
    options resolve through the tuned table (nb becomes the mesh tile
    size), and per-problem solutions come back accurate with info."""
    from slate_tpu.serve.batch import posv_packed_mesh

    mesh = mesh24()
    sizes = [48, 64]
    ops_ = [_spd_stack(rng, 1, s)[0] for s in sizes]
    rhs_ = [jnp.asarray(rng.standard_normal((s, 2))) for s in sizes]
    tbl = _table({"posv|n=128|dtype=float64|grid=2x4":
                  {"bcast_impl": "ring", "lookahead": 0, "nb": 8}})
    with use_tuned_table(tbl):
        xs, info = posv_packed_mesh(ops_, rhs_, mesh, bins=(64,))
    assert int(info) == 0
    for i, s in enumerate(sizes):
        ref = np.linalg.solve(np.asarray(ops_[i]), np.asarray(rhs_[i]))
        assert np.abs(np.asarray(xs[i]) - ref).max() < 1e-8


# ---------------------------------------------------------------------------
# executable cache: steady-state zero retraces (trace-counter asserted)
# ---------------------------------------------------------------------------


def test_cache_steady_state_zero_retrace(rng):
    cache = ExecutableCache()
    before_counts = dict(serve_metrics.serve_counter_values())
    B, n = 2, 16
    spd = _spd_stack(rng, B, n)
    b = jnp.asarray(rng.standard_normal((B, n, 1)))
    key = make_key("posv_batched", (spd, b), batch=B)
    cache.warmup(key, lambda: posv_batched, (spd, b))
    assert cache.trace_count(key) == 1
    snap = cache.snapshot_traces()
    # steady state: fresh data, same shapes -> same key, zero retraces
    for _ in range(4):
        spd2 = _spd_stack(rng, B, n)
        b2 = jnp.asarray(rng.standard_normal((B, n, 1)))
        key2 = make_key("posv_batched", (spd2, b2), batch=B)
        assert key2 == key
        prog = cache.get_or_build(key2, lambda: posv_batched)
        jax.block_until_ready(prog(spd2, b2)[0])
    assert cache.trace_count(key) == 1
    cache.assert_steady(snap)  # must not raise
    # a NEW shape is a new key and exactly one new trace
    b3 = jnp.asarray(rng.standard_normal((B, n, 3)))
    key3 = make_key("posv_batched", (spd, b3), batch=B)
    assert key3 != key
    prog3 = cache.get_or_build(key3, lambda: posv_batched)
    jax.block_until_ready(prog3(spd, b3)[0])
    assert cache.trace_count(key3) == 1
    counts = serve_metrics.serve_counter_values()
    assert counts["cache_hits"] - before_counts["cache_hits"] == 4
    assert counts["cache_misses"] - before_counts["cache_misses"] == 2
    assert counts["traces"] - before_counts["traces"] == 2
    # a retrace past steady state must trip the assertion
    cache._trace_counts[key] += 1
    with pytest.raises(AssertionError, match="retraced"):
        cache.assert_steady(snap)


# ---------------------------------------------------------------------------
# tuned-table resolution: explicit > context > env > tuned > auto
# ---------------------------------------------------------------------------


def _table(entries):
    return {"schema": TUNED_SCHEMA, "version": TUNED_VERSION,
            "entries": entries}


def test_tuned_table_resolution_precedence(monkeypatch):
    from slate_tpu.parallel.comm import BCAST_IMPL_ENV, use_bcast_impl
    from slate_tpu.serve.table import AUTOTUNE_ENV

    monkeypatch.delenv(BCAST_IMPL_ENV, raising=False)
    monkeypatch.delenv(AUTOTUNE_ENV, raising=False)
    tbl = _table({"potrf|n=96|dtype=float64|grid=2x4":
                  {"bcast_impl": "ring", "lookahead": 2, "nb": 16}})
    with use_tuned_table(tbl):
        # tuned beats auto: every unset knob fills from the table
        got = resolve_request_options(None, "potrf", 96, "float64", (2, 4))
        assert got[Option.BcastImpl] == "ring"
        assert got[Option.Lookahead] == 2
        assert got[Option.BlockSize] == 16
        # nearest-n fallback inside the same (op, dtype, grid) family
        near = resolve_request_options(None, "potrf", 128, "float64", (2, 4))
        assert near[Option.BcastImpl] == "ring"
        # explicit beats tuned
        got = resolve_request_options({Option.BcastImpl: "psum",
                                       Option.Lookahead: 0},
                                      "potrf", 96, "float64", (2, 4))
        assert got[Option.BcastImpl] == "psum"
        assert got[Option.Lookahead] == 0
        # context beats tuned: the tuned tier must stay silent so
        # comm.resolve_bcast_impl later picks the context value
        with use_bcast_impl("doubling"):
            got = resolve_request_options(None, "potrf", 96, "float64",
                                          (2, 4))
            assert Option.BcastImpl not in got
        # env beats tuned, same mechanism
        monkeypatch.setenv(BCAST_IMPL_ENV, "psum")
        got = resolve_request_options(None, "potrf", 96, "float64", (2, 4))
        assert Option.BcastImpl not in got
        monkeypatch.delenv(BCAST_IMPL_ENV)
        # Option.AutoTune=off (and the env switch) silence the tier
        got = resolve_request_options({Option.AutoTune: "off"}, "potrf",
                                      96, "float64", (2, 4))
        assert Option.BcastImpl not in got and Option.Lookahead not in got
        monkeypatch.setenv(AUTOTUNE_ENV, "0")
        got = resolve_request_options(None, "potrf", 96, "float64", (2, 4))
        assert Option.BcastImpl not in got
    # no table at all: pass-through
    with use_tuned_table(None):
        monkeypatch.delenv(AUTOTUNE_ENV, raising=False)
        got = resolve_request_options({"lookahead": 3}, "potrf", 96,
                                      "float64", (2, 4))
        assert got == {"lookahead": 3}


def test_committed_tuned_table_valid():
    """The committed artifact must load, validate, and resolve."""
    from slate_tpu.serve.table import load_tuned_table, validate_table

    doc = load_tuned_table()
    assert doc is not None, "artifacts/serve/tuned.json missing or invalid"
    assert validate_table(doc) == []
    assert doc["entries"], "tuned table has no entries"


# ---------------------------------------------------------------------------
# router: admission + accuracy-class dispatch
# ---------------------------------------------------------------------------


def test_router_accuracy_class_dispatch(rng):
    from slate_tpu.serve.router import Router

    before = dict(serve_metrics.serve_counter_values())
    router = Router(bins=(32,), hbm_budget=1 << 30)
    n = 32
    # friendly: well-conditioned operator -> cheap nopiv+IR class
    good = jnp.asarray(rng.standard_normal((n, n)) + n * np.eye(n))
    b = jnp.asarray(rng.standard_normal((n, 2)))
    x = router.solve("gesv", good, b)
    assert np.abs(np.asarray(good @ x - b)).max() < 1e-8
    # hostile: planted ill-conditioned operator (prescribed spectrum,
    # cond 1e9 >> CONDEST_THRESHOLD 1e7) -> pp + GMRES-IR class
    q1, _ = np.linalg.qr(rng.standard_normal((n, n)))
    q2, _ = np.linalg.qr(rng.standard_normal((n, n)))
    sing = np.logspace(0, -9, n)
    bad = jnp.asarray(q1 @ np.diag(sing) @ q2)
    xb = router.solve("gesv", bad, b)
    resid = np.abs(np.asarray(bad @ xb - b)).max()
    assert resid < 1e-4  # cond 1e9: GMRES-IR still lands a usable answer
    counts = serve_metrics.serve_counter_values()
    assert counts["class_friendly"] - before["class_friendly"] >= 1
    assert counts["class_hostile"] - before["class_hostile"] >= 1
    # stationary operator: second solve hits the condest memo
    ch0 = counts["condest_cache_hits"]
    router.solve("gesv", good, jnp.asarray(rng.standard_normal((n, 2))))
    counts = serve_metrics.serve_counter_values()
    assert counts["condest_cache_hits"] - ch0 >= 1
    # admission: a request over the modeled HBM bound is rejected
    tiny = Router(bins=(32,), hbm_budget=10_000)
    with pytest.raises(SlateError, match="admission"):
        tiny.solve("posv", _spd_stack(rng, 1, n)[0], b)
    # a failed factorization is surfaced, never silently served: a
    # non-SPD operand through the posv class reports its info
    with pytest.raises(SlateError, match="nonzero info"):
        router.solve("posv", jnp.asarray(-np.eye(n)), b)


# ---------------------------------------------------------------------------
# stationary-operator caches on the mesh: condest memo, ozaki presplit
# ---------------------------------------------------------------------------


def test_condest_memo_on_factor(rng):
    from slate_tpu.parallel.dist import from_dense
    from slate_tpu.parallel.dist_aux import norm_dist, pocondest_dist
    from slate_tpu.parallel.dist_chol import potrf_dist
    from slate_tpu.types import Norm

    mesh = mesh24()
    n, nb = 64, 8
    a = np.asarray(_spd_stack(rng, 1, n)[0])
    ad = from_dense(jnp.asarray(a), mesh, nb, diag_pad_one=True)
    l, info = potrf_dist(ad)
    assert int(info) == 0
    anorm = norm_dist(Norm.One, from_dense(jnp.asarray(a), mesh, nb))
    before = serve_metrics.serve_counter_values()["condest_cache_hits"]
    r1 = pocondest_dist(l, anorm)
    r2 = pocondest_dist(l, anorm)  # memoized on the factor object
    assert float(r1) == float(r2)
    hits = serve_metrics.serve_counter_values()["condest_cache_hits"]
    assert hits - before == 1
    # a different probe config is a different memo row, not a stale hit
    r3 = pocondest_dist(l, anorm, iters=3)
    assert serve_metrics.serve_counter_values()["condest_cache_hits"] \
        - before == 1
    assert float(r3) > 0


def test_ozaki_presplit_bitwise_and_cached(rng):
    from slate_tpu.parallel.dist import from_dense, to_dense
    from slate_tpu.parallel.summa import (
        clear_ozaki_split_cache,
        gemm_summa_ozaki,
        ozaki_presplit_cached,
    )

    mesh = mesh24()
    n, nb = 96, 8
    a = jnp.asarray(rng.standard_normal((n, n)))
    b = jnp.asarray(rng.standard_normal((n, n)))
    ad = from_dense(a, mesh, nb)
    bd = from_dense(b, mesh, nb)
    clear_ozaki_split_cache()
    before = dict(serve_metrics.serve_counter_values())
    split = ozaki_presplit_cached(ad)
    inline = to_dense(gemm_summa_ozaki(1.0, ad, bd))
    pre = to_dense(gemm_summa_ozaki(1.0, ad, bd, a_split=split))
    np.testing.assert_array_equal(np.asarray(inline), np.asarray(pre))
    # second lookup on the same tile buffer is a hit
    split2 = ozaki_presplit_cached(ad)
    assert split2.qa is split.qa
    counts = serve_metrics.serve_counter_values()
    assert counts["ozaki_presplits"] - before["ozaki_presplits"] == 1
    assert counts["ozaki_presplit_hits"] - before["ozaki_presplit_hits"] == 1


def test_prefactor_memo_stationary_operator(rng):
    """The mixed ladder's f32 factor + distributed A are reused across
    requests against the same dense operand object (and through them,
    the Ozaki planes) — the stationary-A serving stream."""
    from slate_tpu.parallel.dist_refine import (
        _prefactor_cached,
        clear_prefactor_cache,
    )

    mesh = mesh24()
    n = 64
    a = _spd_stack(rng, 1, n)[0]
    clear_prefactor_cache()
    pre1 = _prefactor_cached("posv", a, mesh, 8, None)
    pre2 = _prefactor_cached("posv", a, mesh, 8, None)
    assert pre1[0].tiles is pre2[0].tiles  # factor reused, not recomputed
    assert pre1[3].tiles is pre2[3].tiles  # distributed A reused
    # a different operand object misses
    a2 = _spd_stack(rng, 1, n)[0]
    pre3 = _prefactor_cached("posv", a2, mesh, 8, None)
    assert pre3[0].tiles is not pre1[0].tiles
    clear_prefactor_cache()


# ---------------------------------------------------------------------------
# serve.* counters land in RunReports and gate
# ---------------------------------------------------------------------------


def test_router_admission_models_qr_eig():
    """ISSUE 15: QR/eig requests admit on their OWN memory models (the
    multi-array aux carries), not the getrf_nopiv fallback that
    over-admitted them — pure model arithmetic, no dispatch."""
    from slate_tpu.obs import memmodel
    from slate_tpu.serve.router import Router

    router = Router(hbm_budget=16 * 2**30)
    grid = (1, 1)
    for op, model_op in (("geqrf", "geqrf"), ("gels", "geqrf"),
                         ("heev", "he2hb"), ("he2hb", "he2hb")):
        expect = memmodel.predict_max_n(
            16 * 2**30, op=model_op, nb=max(router.nb, 8), grid=grid,
            dtype="float64")
        assert router.max_n(op) == expect, op
    # the over-admission contrast: the eig chain admits strictly less
    # than the LU fallback would have granted it
    assert router.max_n("heev") < router.max_n("gesv")
    with pytest.raises(SlateError, match="admission"):
        router.admit("heev", router.max_n("heev") + 8 * 4 * 256)


def test_stats_export_grows_num_and_sched_families():
    """ISSUE 15 satellite: one scrape surfaces latency + schedule +
    health together — the Prometheus text grows num.*/sched.* families
    from both the live registry and committed artifacts."""
    from slate_tpu.obs import numerics
    from slate_tpu.serve import stats

    numerics.reset()
    numerics.record_qr_orth("geqrf", 3e-15)
    text = stats.prometheus_text()
    assert "slate_tpu_num_qr_orth_loss_max" in text
    assert "# TYPE slate_tpu_num_qr_orth_loss_max gauge" in text
    assert "slate_tpu_num_qr_orth_margin" in text  # the registry series
    numerics.reset()
    # offline: a numwatch RunReport and a FlightReport format through
    # the same exposition
    rep = {"values": {"num.qr_orth_margin_fused": 1e-15,
                      "sched.model_bytes": 61440.0},
           "num": {"monitored": 2.0}}
    off = stats.prometheus_text(stats.snapshot_from_report(rep))
    assert "slate_tpu_num_qr_orth_margin_fused" in off
    assert "slate_tpu_sched_model_bytes" in off
    assert "slate_tpu_num_monitored" in off


def test_serve_report_section():
    from slate_tpu.obs import report
    from slate_tpu.serve.metrics import serve_count

    serve_count("requests")
    rep = report.make_report("serve_section_test")
    assert report.validate_report(rep) == []
    assert rep["serve"]["requests"] >= 1
    vals = report.load_values(rep)
    assert vals.get("serve_requests", 0) >= 1
    # regression direction: cache misses rising is a failure
    old = dict(vals)
    new = dict(vals)
    new["serve_cache_misses"] = old.get("serve_cache_misses", 0) * 4 + 8
    old["serve_cache_misses"] = old.get("serve_cache_misses", 0) + 1
    failures, _ = report.check_regression(new, old, threshold=1.5)
    assert any("serve_cache_misses" in f for f in failures)

# ---------------------------------------------------------------------------
# graceful degradation (ISSUE 12 satellite): retry / resume / reject
# ---------------------------------------------------------------------------


def _resilient_router(opts):
    from slate_tpu.serve.router import Router

    return Router(mesh=mesh24(), nb=8, bins=(64,), opts=opts)


def _spd_one(rng, n=64):
    g = rng.standard_normal((n, n))
    return jnp.asarray(g @ g.T / n + 2 * np.eye(n))


def test_router_retries_transient_fterror(rng):
    """A transient SDC under a fail-stop FT policy costs ONE Recompute
    retry (serve.retries), not a failed request."""
    from slate_tpu.ft import FtPolicy, inject

    router = _resilient_router({Option.FaultTolerance: FtPolicy.Detect})
    a = _spd_one(rng)
    b = jnp.asarray(rng.standard_normal((64, 2)))
    before = serve_metrics.serve_counter_values()["retries"]
    f = inject.seeded_fault(12, "potrf", 8, (2, 4), phase="panel")
    with inject.fault_scope(inject.FaultPlan([f])):
        x = router.solve("posv", a, b)
    after = serve_metrics.serve_counter_values()["retries"]
    assert after == before + 1
    resid = np.abs(np.asarray(a) @ np.asarray(x) - np.asarray(b)).max()
    assert resid < 1e-8


def test_router_resumes_preempted_request(rng):
    """A preempted checkpointed factorization resumes from its snapshot
    (serve.resumes) and the request completes."""
    from slate_tpu.ft import inject

    router = _resilient_router({Option.Checkpoint: 3})
    a = _spd_one(rng)
    b = jnp.asarray(rng.standard_normal((64, 2)))
    before = serve_metrics.serve_counter_values()["resumes"]
    with inject.fault_scope(inject.FaultPlan([inject.KillFault("potrf", 4)])):
        x = router.solve("posv", a, b)
    after = serve_metrics.serve_counter_values()["resumes"]
    assert after == before + 1
    resid = np.abs(np.asarray(a) @ np.asarray(x) - np.asarray(b)).max()
    assert resid < 1e-8


def test_router_rejects_unresumable_preemption(rng):
    """A kill BEFORE the first snapshot (and a re-kill on resume) is
    admission-rejected with a structured error — never served NaNs."""
    from slate_tpu.ft import inject

    router = _resilient_router({Option.Checkpoint: 3})
    a = _spd_one(rng)
    b = jnp.asarray(rng.standard_normal((64, 2)))
    before = serve_metrics.serve_counter_values()["admission_rejects"]
    with inject.fault_scope(inject.FaultPlan([inject.KillFault("potrf", 1)])):
        with pytest.raises(SlateError, match="unresumable"):
            router.solve("posv", a, b)
    with inject.fault_scope(inject.FaultPlan(
        [inject.KillFault("potrf", 4, persist=True)]
    )):
        with pytest.raises(SlateError, match="re-preempted"):
            router.solve("posv", a, b)
    after = serve_metrics.serve_counter_values()["admission_rejects"]
    assert after == before + 2


def _nested_ok(tr):
    """Phase spans nest correctly: every child interval lies inside an
    enclosing span named by its parent, and depths are consistent."""
    for ph in tr.phases:
        assert ph["t1"] >= ph["t0"] >= tr.t0
        if ph["parent"] is None:
            assert ph["depth"] == 0
        else:
            assert ph["depth"] >= 1
            encl = [p for p in tr.phases
                    if p["name"] == ph["parent"] and p is not ph
                    and p["t0"] <= ph["t0"] and p["t1"] >= ph["t1"]
                    and p["depth"] == ph["depth"] - 1]
            assert encl, (ph["name"], ph["parent"])


# ---------------------------------------------------------------------------
# request-level observability (ISSUE 14): trace completeness across the
# degradation ladder, disabled-mode honesty, SLA/export surfaces.  The
# ladder cases reuse the EXACT router opts/shapes of the degradation
# tests above (NumMonitor pinned off where they resolved off), so every
# mesh program is already compiled — lean by construction.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("case,want", [
    ("clean", "served"),
    ("ft_retry", "served_retry"),
    ("resume", "served_resume"),
    ("growth_abort", "served_growth_retry"),
    ("reject", "reject_unresumable"),
])
def test_request_trace_degradation_ladder(rng, case, want):
    """Every Router path terminates its RequestTrace with exactly ONE
    outcome attributing the exit to one cause, phase spans nest, and
    served requests land in the (op, class, outcome)-tagged latency
    histogram."""
    from slate_tpu import obs
    from slate_tpu.ft import FtPolicy, inject
    from slate_tpu.obs.metrics import REGISTRY
    from slate_tpu.serve import trace as rtrace

    n = 64
    a = _spd_one(rng)
    b = jnp.asarray(rng.standard_normal((n, 2)))
    nmoff = {Option.NumMonitor: "off"}
    with obs.force_enabled(True):
        before = len(rtrace.finished_traces())
        if case == "clean":
            router = _resilient_router({Option.Checkpoint: 3, **nmoff})
            router.solve("posv", a, b)
        elif case == "ft_retry":
            router = _resilient_router(
                {Option.FaultTolerance: FtPolicy.Detect, **nmoff})
            f = inject.seeded_fault(12, "potrf", 8, (2, 4), phase="panel")
            with inject.fault_scope(inject.FaultPlan([f])):
                router.solve("posv", a, b)
        elif case == "resume":
            router = _resilient_router({Option.Checkpoint: 3, **nmoff})
            with inject.fault_scope(
                inject.FaultPlan([inject.KillFault("potrf", 4)])
            ):
                router.solve("posv", a, b)
        elif case == "growth_abort":
            router = _resilient_router({Option.Checkpoint: 3,
                                        Option.NumMonitor: "on"})
            g = rng.standard_normal((n, n)) + n * np.eye(n)
            g[0, 0] = 1e-9  # nopiv growth explodes; pp retry swaps it
            router.solve("gesv", jnp.asarray(g), b)
        elif case == "reject":
            router = _resilient_router({Option.Checkpoint: 3, **nmoff})
            with inject.fault_scope(
                inject.FaultPlan([inject.KillFault("potrf", 1)])
            ):
                with pytest.raises(SlateError, match="unresumable"):
                    router.solve("posv", a, b)
        traces = rtrace.finished_traces()[before:]
    assert len(traces) == 1
    tr = traces[0]
    assert tr.outcome == want
    # exactly one terminal: a second finish is a programming error
    with pytest.raises(RuntimeError, match="already terminal"):
        tr.finish("served")
    _nested_ok(tr)
    names = [ph["name"] for ph in tr.phases]
    assert "admission" in names
    if want.startswith("served"):
        assert "factor" in names and "solve" in names
        klass = tr.klass or "friendly"
        hist = [h for h in REGISTRY.histogram_series("serve.latency_s")
                if h["tags"] == {"op": tr.op, "klass": klass,
                                 "outcome": want}]
        assert hist and hist[-1]["count"] >= 1
    if want == "served_retry":
        assert "retry" in names and tr.notes == ["ft_retry"]
    if want == "served_resume":
        assert "resume" in names and tr.notes == ["resume"]
    if want in ("served_retry", "served_resume", "served_growth_retry"):
        # the degradation ladder renders as flow arrows chaining the
        # retry/resume span(s) to the final dispatch — validator-clean
        from slate_tpu.obs import perfetto

        evs = perfetto.request_trace_events([tr])
        assert perfetto.validate_chrome_trace({"traceEvents": evs}) == []
        starts = [e for e in evs if e.get("ph") == "s"]
        ends = [e for e in evs if e.get("ph") == "f"]
        assert starts and len(starts) == len(ends)
    if want == "served_growth_retry":
        # the pivoted retry's factor/solve nest under the retry span
        retried = [ph for ph in tr.phases
                   if ph["parent"] == "retry" and ph["name"] == "factor"]
        assert retried and tr.notes == ["growth_retry"]


def test_request_trace_disabled_honest_and_dispatch_identical(rng):
    """Obs off => ZERO trace allocations (new_trace returns None, the
    finished stream stays empty) and the Router dispatch is
    byte-identical: the solution bits match the traced run's, and the
    batched program's jaxpr is the same traced or not (tracing is
    host-side only — the no-new-collectives contract)."""
    from slate_tpu import obs
    from slate_tpu.obs import perfetto
    from slate_tpu.serve import trace as rtrace
    from slate_tpu.serve.router import Router, _build_batched
    from slate_tpu.serve.stats import prometheus_text, stats_snapshot

    n = 32  # the accuracy-class test's shapes: programs already warm
    good = jnp.asarray(rng.standard_normal((n, n)) + n * np.eye(n))
    b = jnp.asarray(rng.standard_normal((n, 2)))
    router = Router(bins=(32,), hbm_budget=1 << 30)
    with obs.force_enabled(False):
        assert rtrace.new_trace("gesv", n, 8, "float64") is None
        before = len(rtrace.finished_traces())
        x_off = router.solve("gesv", good, b)
        assert len(rtrace.finished_traces()) == before  # zero allocations
    with obs.force_enabled(True):
        x_on = router.solve("gesv", good, b)
        traces = rtrace.finished_traces()[before:]
    assert len(traces) == 1 and traces[0].outcome == "served"
    np.testing.assert_array_equal(np.asarray(x_off), np.asarray(x_on))
    # dispatch-identical: the stacked program's jaxpr is invariant under
    # an armed tracer (host-side spans cannot reach the compiled code)
    fn = _build_batched("posv", "friendly")
    spd = _spd_stack(rng, 1, 16)
    bb = jnp.asarray(rng.standard_normal((1, 16, 1)))
    j_off = str(jax.make_jaxpr(fn)(spd, bb))
    with obs.force_enabled(True):
        j_on = str(jax.make_jaxpr(fn)(spd, bb))
    assert j_off == j_on
    # export surfaces over the traced request: SLA reduction keys,
    # Perfetto request timeline, Prometheus text
    sla = rtrace.sla_values()
    assert sla["latency_count_gesv_friendly"] >= 1
    p50 = sla["latency_p50_gesv_friendly_s"]
    p99 = sla["latency_p99_gesv_friendly_s"]
    assert 0 <= p50 <= p99
    total = sum(v for k, v in sla.items() if k.startswith("outcome_")
                and not k.startswith("outcome_rate_"))
    assert total == len(rtrace.finished_traces())
    evs = perfetto.request_trace_events(traces)
    assert perfetto.validate_chrome_trace({"traceEvents": evs}) == []
    assert any(e.get("args", {}).get("name") == "serve[friendly]"
               for e in evs if e.get("ph") == "M")
    txt = prometheus_text(stats_snapshot())
    assert "slate_tpu_serve_requests" in txt
    assert 'quantile="0.99"' in txt


def test_request_trace_batch_abort_attributes_siblings(rng):
    """A failing request aborts the whole solve_batch call; its OWN
    trace carries the cause (failed_info) and every sibling terminates
    as reject_batch_abort — no trace leaks unterminated."""
    from slate_tpu import obs
    from slate_tpu.serve import trace as rtrace
    from slate_tpu.serve.router import Router

    n = 32
    router = Router(bins=(32,), hbm_budget=1 << 30)
    good = _spd_stack(rng, 1, n)[0]
    b = jnp.asarray(rng.standard_normal((n, 2)))
    with obs.force_enabled(True):
        before = len(rtrace.finished_traces())
        with pytest.raises(SlateError, match="nonzero info"):
            router.solve_batch([("posv", good, b),
                                ("posv", jnp.asarray(-np.eye(n)), b)])
        traces = rtrace.finished_traces()[before:]
    assert sorted(t.outcome for t in traces) \
        == ["failed_info", "reject_batch_abort"]


def test_router_growth_abort_retries_with_pivoting(rng):
    """ISSUE 13 satellite (ROADMAP "close the control loop"): on the
    monitored checkpointed path, gesv tries the cheap no-pivot factor
    first; a mid-k-loop GrowthAbort escalates to partial pivoting as
    exactly one retry (serve.retries), and a healthy operand stays on
    the no-pivot fast path with zero retries."""
    router = _resilient_router({Option.Checkpoint: 3,
                                Option.NumMonitor: "on"})
    n = 64
    g = rng.standard_normal((n, n)) + n * np.eye(n)
    g[0, 0] = 1e-9  # tiny leading pivot: nopiv growth explodes; pp swaps
    a = jnp.asarray(g)
    b = jnp.asarray(rng.standard_normal((n, 2)))
    before = serve_metrics.serve_counter_values()["retries"]
    x = router.solve("gesv", a, b)
    after = serve_metrics.serve_counter_values()["retries"]
    assert after == before + 1
    resid = np.abs(np.asarray(a) @ np.asarray(x) - np.asarray(b)).max()
    assert resid < 1e-8

    good = jnp.asarray(rng.standard_normal((n, n)) + n * np.eye(n))
    before = serve_metrics.serve_counter_values()["retries"]
    x2 = router.solve("gesv", good, b)
    after = serve_metrics.serve_counter_values()["retries"]
    assert after == before
    resid2 = np.abs(np.asarray(good) @ np.asarray(x2) - np.asarray(b)).max()
    assert resid2 < 1e-8
