"""Fused Pallas panel kernels (ISSUE 6): Option.PanelImpl end-to-end.

Contracts under test, on CPU with every kernel running under the Pallas
interpreter (the tier-1 parity story — the same kernels compile for the
MXU on a real TPU backend):

1. Every fused panel kernel matches its XLA reference: the QR panels are
   BITWISE (same op sequence inside and outside the kernel); the
   Cholesky/LU panels use the explicit-inverse solve (the MAGMA
   trtri+gemm idiom ``_potrf_scan`` already ships) and match to the
   documented O(eps * cond(diag block)) class.
2. ``Option.PanelImpl = xla`` reproduces today's results bitwise (it IS
   today's trace), and ``auto`` resolves to xla off-TPU — the default
   tier-1 schedules are untouched.
3. The option plumbs through driver ``opts``, the ``use_panel_impl``
   context, and the ``SLATE_TPU_PANEL_IMPL`` environment default, with
   explicit-argument > context > environment precedence (the
   ``pallas_call`` eqn in the traced jaxpr is the fingerprint).
4. Non-multiple-of-nb sizes ride the padding contracts unchanged under
   both lowerings; complex dtypes fall back to xla even when pallas is
   requested.
5. The fused ABFT SUMMA consume accumulates the Huang-Abraham partial
   sums in-pass: the online discrepancy is tiny on clean runs and lights
   up under an injected broadcast-phase fault.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import cpu_devices

from slate_tpu.ops import pallas_ops as po
from slate_tpu.parallel import from_dense, make_mesh, to_dense
from slate_tpu.parallel.dist_chol import potrf_dist
from slate_tpu.parallel.dist_lu import getrf_nopiv_dist
from slate_tpu.types import Option

N, NB = 64, 8
DTYPES = [jnp.float32, jnp.float64]


def mesh24():
    return make_mesh(2, 4, devices=cpu_devices(8))


def _spd(rng, n, dtype):
    g = rng.standard_normal((n, n))
    return jnp.asarray(g @ g.T + n * np.eye(n), dtype)


def _diag_dom(rng, n, dtype):
    return jnp.asarray(
        rng.standard_normal((n, n)) + n * np.eye(n), dtype
    )


def _tol(dtype, scale=1.0):
    return 100 * NB * float(jnp.finfo(dtype).eps) * scale


# ---------------------------------------------------------------------------
# kernel-level parity vs the XLA references (interpret mode)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
def test_chol_diag_inv_parity(rng, dtype):
    a = _spd(rng, NB, dtype)
    l, x = po.chol_diag_inv_pallas(a)
    l_ref = jax.lax.linalg.cholesky(a)
    x_ref = jax.lax.linalg.triangular_solve(
        l_ref[None], jnp.eye(NB, dtype=dtype)[None], left_side=True,
        lower=True, transpose_a=False,
    )[0]
    anorm = float(jnp.abs(a).max())
    assert np.abs(np.asarray(l) - np.asarray(l_ref)).max() < _tol(dtype, anorm)
    assert np.abs(np.asarray(x) - np.asarray(x_ref)).max() < _tol(
        dtype, float(jnp.abs(x_ref).max()) * anorm
    )


@pytest.mark.parametrize("dtype", DTYPES)
def test_chol_panel_tiles_parity(rng, dtype):
    a = _spd(rng, NB, dtype)
    tiles = jnp.asarray(rng.standard_normal((5, NB, NB)), dtype)
    lkk, solved = po.chol_panel_tiles_pallas(a, tiles)
    l_ref = np.linalg.cholesky(np.asarray(a, np.float64))
    s_ref = np.asarray(tiles, np.float64) @ np.linalg.inv(l_ref).T
    assert np.abs(np.asarray(lkk, np.float64) - l_ref).max() < _tol(dtype, NB)
    assert np.abs(np.asarray(solved, np.float64) - s_ref).max() < _tol(
        dtype, float(np.abs(s_ref).max()) * NB
    )


@pytest.mark.parametrize("dtype", DTYPES)
def test_lu_panel_tiles_parity(rng, dtype):
    a = _diag_dom(rng, NB, dtype)
    tiles = jnp.asarray(rng.standard_normal((4, NB, NB)), dtype)
    lu, csolved = po.lu_panel_tiles_pallas(a, tiles)
    lun = np.asarray(lu, np.float64)
    L = np.tril(lun, -1) + np.eye(NB)
    U = np.triu(lun)
    assert np.abs(L @ U - np.asarray(a, np.float64)).max() < _tol(dtype, NB)
    c_ref = np.asarray(tiles, np.float64) @ np.linalg.inv(U)
    assert np.abs(np.asarray(csolved, np.float64) - c_ref).max() < _tol(
        dtype, float(np.abs(c_ref).max()) * NB
    )
    rsolved = po.lu_rowsolve_tiles_pallas(lu, tiles)
    r_ref = np.linalg.inv(L) @ np.asarray(tiles, np.float64)
    assert np.abs(np.asarray(rsolved, np.float64) - r_ref).max() < _tol(
        dtype, float(np.abs(r_ref).max()) * NB
    )


@pytest.mark.parametrize("dtype", DTYPES)
def test_qr_panel_bitwise(rng, dtype):
    from slate_tpu.linalg.qr import _larft, _larft_v, _panel_qr, _panel_qr_offset

    a = jnp.asarray(rng.standard_normal((40, NB)), dtype)
    vr, tau, t = po.qr_panel_pallas(a)
    vr_ref, tau_ref = _panel_qr(a)
    t_ref = _larft(vr_ref, tau_ref)
    np.testing.assert_array_equal(np.asarray(vr), np.asarray(vr_ref))
    np.testing.assert_array_equal(np.asarray(tau), np.asarray(tau_ref))
    np.testing.assert_array_equal(np.asarray(t), np.asarray(t_ref))

    # offset variant with a nonzero (and traced-capable) pivot row
    masked = jnp.where(jnp.arange(40)[:, None] >= NB, a, 0)
    r, v, tau2, t2 = po.qr_panel_offset_pallas(masked, NB)
    r_ref, v_ref, tau2_ref = _panel_qr_offset(masked, NB)
    t2_ref = _larft_v(v_ref, tau2_ref)
    for got, ref in [(r, r_ref), (v, v_ref), (tau2, tau2_ref), (t2, t2_ref)]:
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_ft_summa_update_parity(rng):
    I, J = 4, 3
    acc = jnp.asarray(rng.standard_normal((I, J, NB, NB)))
    pan = jnp.asarray(rng.standard_normal((I, NB, NB)))
    urow = jnp.asarray(rng.standard_normal((J, NB, NB)))
    w1 = jnp.asarray(rng.standard_normal(I))
    w2 = jnp.asarray(rng.standard_normal(I))
    part0 = jnp.asarray(rng.standard_normal((2, J, NB, NB)))
    out, part = po.ft_summa_update_pallas(acc, pan, urow, w1, w2, part0)
    upd = np.einsum("iab,jbc->ijac", np.asarray(pan), np.asarray(urow))
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(acc) + upd, rtol=0, atol=1e-12
    )
    p_ref = np.asarray(part0) + np.stack([
        np.einsum("i,ijab->jab", np.asarray(w1), upd),
        np.einsum("i,ijab->jab", np.asarray(w2), upd),
    ])
    np.testing.assert_allclose(np.asarray(part), p_ref, rtol=0, atol=1e-12)


# ---------------------------------------------------------------------------
# driver-level parity: mesh factorizations under both lowerings
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [N, N - 4], ids=["aligned", "ragged-tail"])
@pytest.mark.parametrize("dtype", DTYPES)
def test_potrf_dist_pallas_parity(rng, n, dtype):
    mesh = mesh24()
    a = _spd(rng, n, dtype)
    ad = from_dense(a, mesh, NB, diag_pad_one=True)
    l_x, info_x = potrf_dist(ad, panel_impl="xla")
    l_p, info_p = potrf_dist(ad, panel_impl="pallas")
    assert int(info_x) == 0 and int(info_p) == 0
    lx = np.tril(np.asarray(to_dense(l_x), np.float64))[:n, :n]
    lp = np.tril(np.asarray(to_dense(l_p), np.float64))[:n, :n]
    an = np.asarray(a, np.float64)
    scale = np.abs(an).max() * n
    # both lowerings must factor A to the dtype's backward-error class
    assert np.abs(lx @ lx.T - an).max() < _tol(dtype, scale)
    assert np.abs(lp @ lp.T - an).max() < _tol(dtype, scale)


@pytest.mark.parametrize("n", [N, N - 4], ids=["aligned", "ragged-tail"])
@pytest.mark.parametrize("dtype", DTYPES)
def test_getrf_nopiv_dist_pallas_parity(rng, n, dtype):
    mesh = mesh24()
    a = _diag_dom(rng, n, dtype)
    ad = from_dense(a, mesh, NB, diag_pad_one=True)
    outs = {}
    for impl in ("xla", "pallas"):
        lu, info = getrf_nopiv_dist(ad, panel_impl=impl)
        assert int(info) == 0, impl
        outs[impl] = np.asarray(to_dense(lu), np.float64)[:n, :n]
    an = np.asarray(a, np.float64)
    for impl, lun in outs.items():
        rec = (np.tril(lun, -1) + np.eye(n)) @ np.triu(lun)
        assert np.abs(rec - an).max() < _tol(
            dtype, np.abs(an).max() * n
        ), impl


def test_panel_impl_xla_is_todays_trace(rng):
    """``xla`` and off-TPU ``auto`` must produce the IDENTICAL jaxpr —
    the acceptance bar that PanelImpl=xla reproduces today's results
    bitwise (same trace => same program => same bits)."""
    mesh = mesh24()
    ad = from_dense(_spd(rng, N, jnp.float64), mesh, NB, diag_pad_one=True)
    jx = {
        impl: str(jax.make_jaxpr(
            lambda x: potrf_dist(x, panel_impl=impl)
        )(ad))
        for impl in ("xla", "auto")
    }
    assert jx["auto"] == jx["xla"]
    assert "pallas_call" not in jx["xla"]


def test_complex_falls_back_to_xla(rng):
    """Complex panels have no fused kernel: requesting pallas must trace
    the XLA forms rather than fail."""
    mesh = mesh24()
    g = rng.standard_normal((N, N)) + 1j * rng.standard_normal((N, N))
    a = jnp.asarray(g @ g.conj().T + N * np.eye(N), jnp.complex128)
    ad = from_dense(a, mesh, NB, diag_pad_one=True)
    jx = str(jax.make_jaxpr(
        lambda x: potrf_dist(x, panel_impl="pallas")
    )(ad))
    assert "pallas_call" not in jx
    l, info = potrf_dist(ad, panel_impl="pallas")
    assert int(info) == 0


# ---------------------------------------------------------------------------
# option plumbing: opts / context / environment, with precedence
# ---------------------------------------------------------------------------


def _uses_pallas(run):
    jax.clear_caches()  # trace-time dispatch (cf. bcast impl tests)
    return "pallas_call" in str(jax.make_jaxpr(run)())


def test_panel_impl_plumbs_through_driver_opts(rng):
    from slate_tpu.parallel import potrf_mesh

    mesh = mesh24()
    a = _spd(rng, N, jnp.float64)
    run = lambda impl: (lambda: potrf_mesh(a, mesh, nb=NB,
                                           opts={Option.PanelImpl: impl}))
    assert not _uses_pallas(run("xla"))
    assert _uses_pallas(run("pallas"))
    assert not _uses_pallas(run("auto"))  # off-TPU auto -> xla


def test_panel_impl_context_and_env_defaults(rng, monkeypatch):
    mesh = mesh24()
    ad = from_dense(_spd(rng, N, jnp.float64), mesh, NB, diag_pad_one=True)

    def run(**kw):
        return lambda: potrf_dist(ad, **kw)

    # environment default
    monkeypatch.setenv(po.PANEL_IMPL_ENV, "pallas")
    assert _uses_pallas(run())
    # context beats environment
    with po.use_panel_impl("xla"):
        assert not _uses_pallas(run())
        # explicit argument beats context
        assert _uses_pallas(run(panel_impl="pallas"))
    # unknown values fail loudly, at resolve time
    with pytest.raises(ValueError, match="unknown panel impl"):
        potrf_dist(ad, panel_impl="fpga")
    monkeypatch.setenv(po.PANEL_IMPL_ENV, "abacus")
    with pytest.raises(ValueError, match="unknown panel impl"):
        potrf_dist(ad)


def test_resolve_default_is_auto(monkeypatch):
    monkeypatch.delenv(po.PANEL_IMPL_ENV, raising=False)
    assert po.resolve_panel_impl() == "auto"
    assert po.resolve_panel_impl("pallas") == "pallas"


# ---------------------------------------------------------------------------
# single-chip facades: QR panels are bitwise across lowerings
# ---------------------------------------------------------------------------


def test_geqrf_bitwise_across_impls(rng):
    from slate_tpu.linalg.qr import geqrf_array, geqrf_scan_array

    a = jnp.asarray(rng.standard_normal((96, 40)))
    jax.clear_caches()
    f_x = geqrf_array(a)
    fs_x = geqrf_scan_array(a, nb=16)
    with po.use_panel_impl("pallas"):
        jax.clear_caches()
        f_p = geqrf_array(a)
        fs_p = geqrf_scan_array(a, nb=16)
    jax.clear_caches()
    np.testing.assert_array_equal(np.asarray(f_x.vr), np.asarray(f_p.vr))
    np.testing.assert_array_equal(np.asarray(f_x.t), np.asarray(f_p.t))
    np.testing.assert_array_equal(np.asarray(fs_x.r), np.asarray(fs_p.r))
    np.testing.assert_array_equal(np.asarray(fs_x.v), np.asarray(fs_p.v))
    np.testing.assert_array_equal(np.asarray(fs_x.t), np.asarray(fs_p.t))


# ---------------------------------------------------------------------------
# fused ABFT consume: in-pass Huang-Abraham discrepancy
# ---------------------------------------------------------------------------


def _online_disc():
    from slate_tpu.obs import REGISTRY

    for g in REGISTRY.snapshot()["gauges"]:
        if g["name"] == "ft.online_disc":
            return g["value"]
    return None


def test_ft_gemm_online_disc(rng):
    from slate_tpu.ft.abft import gemm_ft
    from slate_tpu.ft.inject import FaultPlan, fault_scope, seeded_fault
    from slate_tpu.ft.policy import FtPolicy

    mesh = mesh24()
    a = jnp.asarray(rng.standard_normal((N, N)))
    b = jnp.asarray(rng.standard_normal((N, N)))
    ref = np.asarray(a) @ np.asarray(b)

    out, _ = gemm_ft(1.0, a, b, mesh, NB, panel_impl="pallas")
    np.testing.assert_allclose(np.asarray(out), ref, rtol=0, atol=1e-10)
    clean = _online_disc()
    assert clean is not None and clean < 1e-8, clean

    # a broadcast-phase fault corrupts the update stream the fused kernel
    # consumes — the in-pass discrepancy must light up (and the host
    # verify still corrects the output)
    f = seeded_fault(3, "gemm", nt=N // NB, grid=(2, 4), phase="bcast")
    with fault_scope(FaultPlan([f])):
        out_f, rep = gemm_ft(
            1.0, a, b, mesh, NB, policy=FtPolicy.Correct, panel_impl="pallas"
        )
    faulted = _online_disc()
    assert faulted > 1e3 * max(clean, 1e-30), (clean, faulted)
    assert rep.action in ("corrected", "recomputed")
    np.testing.assert_allclose(np.asarray(out_f), ref, rtol=0, atol=1e-10)


@pytest.mark.parametrize("op", ["potrf", "getrf_nopiv"])
def test_ft_factor_pallas_clean(rng, op):
    from slate_tpu.ft.abft import getrf_nopiv_ft, potrf_ft

    mesh = mesh24()
    if op == "potrf":
        a = _spd(rng, N, jnp.float64)
        res, info, rep = potrf_ft(a, mesh, NB, panel_impl="pallas")
    else:
        a = _diag_dom(rng, N, jnp.float64)
        res, info, rep = getrf_nopiv_ft(a, mesh, NB, panel_impl="pallas")
    assert int(info) == 0
    assert rep.action == "clean"
    out = np.asarray(to_dense(res), np.float64)
    an = np.asarray(a, np.float64)
    if op == "potrf":
        l = np.tril(out)
        assert np.abs(l @ l.T - an).max() < _tol(jnp.float64, N * np.abs(an).max())
    else:
        rec = (np.tril(out, -1) + np.eye(N)) @ np.triu(out)
        assert np.abs(rec - an).max() < _tol(jnp.float64, N * np.abs(an).max())
