"""Service-layer tests (ISSUE 19): the batch-window queue under an
injectable ManualClock — B-fill vs T-expiry, FIFO within a tenant,
weighted-DRR fairness and starvation freedom, per-tenant budget
rejections, the exactly-one-terminal contract across mid-batch aborts,
the Router.max_n memo, controller hysteresis, and the obs.live
``/queue.json`` + ``/healthz`` scrape surface.

Everything here is meshless (stacked single-chip programs, n = 16 in
one bin) and clock-driven: no test sleeps on wall time to reach a
window deadline, every close is a decision about numbers.
"""

import json
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu import obs
from slate_tpu.obs import REGISTRY
from slate_tpu.serve import metrics as serve_metrics
from slate_tpu.serve import trace as rtrace
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.controller import Hysteresis, ServiceController
from slate_tpu.serve.queue import BatchQueue, ManualClock
from slate_tpu.serve.router import Router
from slate_tpu.types import SlateError

N = 16
BIN = 16


@pytest.fixture
def live_obs():
    """Armed tracer + clean finished-trace stream (the queue opens a
    RequestTrace per submit; these tests assert on terminal outcomes)."""
    obs.reset()
    rtrace.reset()
    with obs.force_enabled():
        yield
    rtrace.reset()
    obs.reset()


def _spd(rng, n=N):
    g = rng.standard_normal((n, n))
    return jnp.asarray(g @ g.T / n + 2 * np.eye(n))


def _counts():
    return dict(serve_metrics.serve_counter_values())


def _make_queue(name, **kw):
    router = Router(bins=(BIN,), hbm_budget=1 << 30,
                    cache=ExecutableCache())
    clk = ManualClock()
    q = BatchQueue(router, max_batch=kw.pop("max_batch", 4),
                   window_s=kw.pop("window_s", 0.005), clock=clk,
                   name=name, **kw)
    return q, clk


# ---------------------------------------------------------------------------
# window closes: B-fill vs T-expiry
# ---------------------------------------------------------------------------


def test_b_fill_closes_before_deadline(rng, live_obs):
    """The Bth compatible submit closes the window immediately — the
    clock never advances, so the deadline CANNOT be the cause."""
    q, _clk = _make_queue("t_bfill", max_batch=3)
    try:
        before = _counts()
        tks = [q.submit("posv", _spd(rng),
                        jnp.asarray(rng.standard_normal(N)))
               for _ in range(3)]
        assert all(tk.done() for tk in tks)
        assert q.dispatch_log[-1]["cause"] == "full"
        assert len(q.dispatch_log[-1]["tickets"]) == 3
        after = _counts()
        assert after["queue_window_full"] - before["queue_window_full"] == 1
        assert after["queue_windows"] - before["queue_windows"] == 1
        for tk in tks:
            assert tk.trace.outcome == "served"
    finally:
        q.close()


def test_t_expiry_closes_underfull_window(rng, live_obs):
    """Below B, nothing dispatches until the injected clock crosses the
    deadline; the close is then attributed to expiry, and each solution
    is bitwise the one-at-a-time Router dispatch."""
    q, clk = _make_queue("t_texp", max_batch=8, window_s=0.005)
    try:
        ops = [_spd(rng) for _ in range(2)]
        rhs = [jnp.asarray(rng.standard_normal(N)) for _ in range(2)]
        tks = [q.submit("posv", a, b) for a, b in zip(ops, rhs)]
        assert q.pump() == 0          # t=0: deadline not reached
        assert not tks[0].done()
        with pytest.raises(SlateError):
            tks[0].result()           # not dispatched yet
        clk.advance(0.005)
        assert q.pump() == 2
        assert q.dispatch_log[-1]["cause"] == "expired"
        ref = Router(bins=(BIN,), hbm_budget=1 << 30,
                     cache=ExecutableCache())
        for tk, a, b in zip(tks, ops, rhs):
            np.testing.assert_array_equal(np.asarray(tk.result()),
                                          np.asarray(ref.solve("posv", a, b)))
    finally:
        q.close()


def test_ticket_wait_times_out(rng, live_obs):
    q, _clk = _make_queue("t_wait", max_batch=8)
    try:
        tk = q.submit("posv", _spd(rng),
                      jnp.asarray(rng.standard_normal(N)))
        with pytest.raises(TimeoutError):
            tk.wait(timeout=0.01)
    finally:
        q.close()


# ---------------------------------------------------------------------------
# dequeue order: FIFO within a tenant, weighted DRR across tenants
# ---------------------------------------------------------------------------


def test_fifo_within_tenant(rng, live_obs):
    q, clk = _make_queue("t_fifo", max_batch=8)
    try:
        tks = [q.submit("posv", _spd(rng),
                        jnp.asarray(rng.standard_normal(N)),
                        tenant="solo")
               for _ in range(5)]
        clk.advance(0.01)
        q.pump()
        served = [seq for seq, _t in q.dispatch_log[-1]["tickets"]]
        assert served == sorted(served)
        assert served == [tk.seq for tk in tks]
    finally:
        q.close()


def _drr_contended(rng, q, clk, per_tenant, k):
    """Submit ``per_tenant`` requests for acme and zeta interleaved into
    one oversubscribed window, then close it at ``max_batch=k``.
    Returns the contended close's (seq, tenant) list and the leftover
    close's."""
    q.max_batch = 100  # no B-fill while loading the window
    tks = []
    for _ in range(per_tenant):
        for tenant in ("acme", "zeta"):
            tks.append(q.submit("posv", _spd(rng),
                                jnp.asarray(rng.standard_normal(N)),
                                tenant=tenant))
    q.max_batch = k
    clk.advance(0.01)
    q.pump()          # contended close: DRR selects k of 2*per_tenant
    clk.advance(0.01)
    q.pump()          # the reopened leftover window expires
    assert all(tk.done() for tk in tks)
    return q.dispatch_log[-2]["tickets"], q.dispatch_log[-1]["tickets"]


def test_drr_equal_weights_split_evenly(rng, live_obs):
    q, clk = _make_queue("t_drr1", window_s=0.005)
    try:
        first, _rest = _drr_contended(rng, q, clk, per_tenant=4, k=4)
        by_tenant = {"acme": 0, "zeta": 0}
        for _seq, tenant in first:
            by_tenant[tenant] += 1
        assert by_tenant == {"acme": 2, "zeta": 2}
    finally:
        q.close()


def test_drr_weighted_fairness_and_starvation_freedom(rng, live_obs):
    """At weights 2:1 a contended close serves acme:zeta in ratio 2:1
    (lag bounded by one max-weight round) and BOTH tenants appear — a
    saturating acme cannot starve zeta.  FIFO holds per tenant across
    the contended close and the leftover's."""
    q, clk = _make_queue("t_drr2", window_s=0.005,
                         weights={"acme": 2.0, "zeta": 1.0})
    try:
        first, rest = _drr_contended(rng, q, clk, per_tenant=6, k=8)
        by_tenant = {"acme": [], "zeta": []}
        for seq, tenant in first + rest:
            by_tenant[tenant].append(seq)
        n_first = {"acme": 0, "zeta": 0}
        for _seq, tenant in first:
            n_first[tenant] += 1
        assert n_first["acme"] == 6 and n_first["zeta"] == 2
        assert min(n_first.values()) > 0  # starvation freedom
        for seqs in by_tenant.values():   # FIFO within each tenant
            assert seqs == sorted(seqs)
    finally:
        q.close()


# ---------------------------------------------------------------------------
# per-tenant budgets
# ---------------------------------------------------------------------------


def test_budget_reject_is_terminal_and_isolated(rng, live_obs):
    """A tenant over its declared budget is refused at SUBMIT with the
    ``reject_budget`` terminal; an unrelated tenant is untouched, and
    dispatch releases every reservation (peak never over budget)."""
    from slate_tpu.serve.budget import request_cost

    cost = request_cost(BIN, 8)
    budget = int(2.5 * cost)          # room for exactly 2 reservations
    q, clk = _make_queue("t_budget", max_batch=8,
                         budgets={"hog": budget})
    try:
        before = _counts()
        for _ in range(2):
            q.submit("posv", _spd(rng),
                     jnp.asarray(rng.standard_normal(N)), tenant="hog")
        with pytest.raises(SlateError, match="budget"):
            q.submit("posv", _spd(rng),
                     jnp.asarray(rng.standard_normal(N)), tenant="hog")
        after = _counts()
        assert after["queue_budget_rejects"] \
            - before["queue_budget_rejects"] == 1
        rejected = [t for t in rtrace.finished_traces()
                    if t.outcome == "reject_budget"]
        assert len(rejected) == 1 and rejected[0].tenant == "hog"
        # the calm tenant's default budget is unaffected by hog's state
        q.submit("posv", _spd(rng),
                 jnp.asarray(rng.standard_normal(N)), tenant="calm")
        clk.advance(0.01)
        q.pump()
        snap = q.ledger.snapshot()
        assert snap["hog"]["reserved_bytes"] == 0
        assert 0 < snap["hog"]["peak_bytes"] <= budget
    finally:
        q.close()


# ---------------------------------------------------------------------------
# admission hardening (REVIEW 19): malformed shapes, degenerate weights
# ---------------------------------------------------------------------------


def test_malformed_shapes_rejected_at_submit(rng, live_obs):
    """A non-square operand or a mismatched rhs is refused at SUBMIT as
    ``reject_admission`` — it never enters a window, so it cannot abort
    a shared batch (or, unguarded, kill the pump worker) at stack/pad
    time, and a well-formed request sharing the queue still serves."""
    q, clk = _make_queue("t_malformed", max_batch=4)
    try:
        with pytest.raises(SlateError, match="square"):
            q.submit("posv", jnp.zeros((N, N - 2)), jnp.zeros((N,)))
        with pytest.raises(SlateError, match="rhs"):
            q.submit("posv", _spd(rng), jnp.zeros((N + 4,)))
        with pytest.raises(SlateError, match="rhs"):
            q.submit("posv", _spd(rng), jnp.zeros((N, 2, 2)))
        assert q.depth() == 0
        outcomes = [t.outcome for t in rtrace.finished_traces()]
        assert outcomes.count("reject_admission") == 3
        assert all(t["reserved_bytes"] == 0
                   for t in q.ledger.snapshot().values())
        tk = q.submit("posv", _spd(rng),
                      jnp.asarray(rng.standard_normal(N)))
        clk.advance(0.01)
        q.pump()
        assert tk.trace.outcome == "served"
    finally:
        q.close()


def test_nonpositive_weight_rejected_at_construction():
    """``--weight t=0`` (or negative/NaN) must fail fast: a tenant whose
    deficit can never reach 1.0 would hard-hang the DRR rotation."""
    from slate_tpu.serve.budget import BudgetLedger

    for bad in (0.0, -1.0, float("nan")):
        with pytest.raises(ValueError, match="weight"):
            BudgetLedger(weights={"t": bad})
    with pytest.raises(ValueError, match="weight"):
        BudgetLedger(default_weight=0.0)


def test_drr_progresses_under_degenerate_runtime_weight(rng, live_obs):
    """Construction validates weights > 0, but a ledger subclass could
    still hand back 0 at dequeue time — selection must force-serve the
    head-of-line tenant instead of spinning the dispatching thread."""
    q, clk = _make_queue("t_degen", max_batch=8)
    try:
        tks = [q.submit("posv", _spd(rng),
                        jnp.asarray(rng.standard_normal(N)),
                        tenant="stuck")
               for _ in range(3)]
        q.ledger.account("stuck").weight = 0.0   # simulate a bad ledger
        clk.advance(0.01)
        assert q.pump() == 3
        assert all(tk.trace.outcome == "served" for tk in tks)
    finally:
        q.close()


def test_deficit_preserved_across_windows(rng, live_obs):
    """Accrued DRR credit survives one window's close while the tenant
    still has entries pending in ANOTHER open window — deficit resets
    only on a full drain, so the one-round service-lag bound holds
    queue-wide, not per window."""
    q, _clk = _make_queue("t_deficit", max_batch=8,
                          weights={"acme": 1.7})
    try:
        b1 = jnp.asarray(rng.standard_normal(N))       # nrhs=1 window
        b2 = jnp.asarray(rng.standard_normal((N, 2)))  # nrhs=2 window
        q.submit("posv", _spd(rng), b1, tenant="acme")
        tk2 = q.submit("posv", _spd(rng), b2, tenant="acme")
        with q._lock:
            k1, k2 = list(q._windows)
        q._close_key(k1, "expired")
        # +1.7 granted, 1 served: the 0.7 credit is KEPT (k2 pending)
        assert q._deficit["acme"] == pytest.approx(0.7)
        q._close_key(k2, "expired")
        assert q._deficit["acme"] == 0.0   # fully drained: reset
        assert tk2.trace.outcome == "served"
    finally:
        q.close()


def test_ticket_seqs_unique_across_queues(rng, live_obs):
    """Ticket numbering is process-wide and atomic: two queues never
    issue the same seq (dispatch logs / FIFO assertions key on it)."""
    qa, _ca = _make_queue("t_seq_a", max_batch=8)
    qb, _cb = _make_queue("t_seq_b", max_batch=8)
    try:
        seqs = [q.submit("posv", _spd(rng),
                         jnp.asarray(rng.standard_normal(N))).seq
                for q in (qa, qb, qa, qb)]
        assert len(set(seqs)) == 4
        qa.drain()
        qb.drain()
    finally:
        qa.close()
        qb.close()


def test_worker_survives_pump_exception(rng, live_obs):
    """A non-SlateError escaping pump() (the REVIEW 19 DoS: one bad
    dispatch) must not kill the service worker — the next pump still
    runs and subsequent requests still serve."""
    from slate_tpu.serve.service import Service

    router = Router(bins=(BIN,), hbm_budget=1 << 30,
                    cache=ExecutableCache())
    svc = Service(router=router, max_batch=2, window_s=0.001,
                  name="t_svc_survive")
    orig_pump = svc.queue.pump
    state = {"boomed": False}

    def flaky_pump():
        if not state["boomed"]:
            state["boomed"] = True
            raise ValueError("boom")
        return orig_pump()

    svc.queue.pump = flaky_pump
    svc.start()
    try:
        x = svc.solve("posv", _spd(rng),
                      jnp.asarray(rng.standard_normal(N)))
        assert np.asarray(x).shape == (N,)
        assert state["boomed"]
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# exactly one terminal per request, including mid-batch aborts
# ---------------------------------------------------------------------------


def test_mid_batch_abort_exactly_one_terminal(rng, live_obs):
    """A non-SPD operand inside a posv window aborts the WHOLE dispatch:
    the offender terminates ``failed_info``, every sibling
    ``reject_batch_abort``, every ticket fails, and no trace carries a
    second outcome (finish would raise if one did)."""
    q, clk = _make_queue("t_abort", max_batch=8)
    try:
        good = [q.submit("posv", _spd(rng),
                         jnp.asarray(rng.standard_normal(N)))
                for _ in range(2)]
        bad = q.submit("posv", jnp.asarray(-np.eye(N)),
                       jnp.asarray(rng.standard_normal(N)))
        clk.advance(0.01)
        with pytest.raises(SlateError, match="info"):
            q.pump()
        assert bad.trace.outcome == "failed_info"
        for tk in good:
            assert tk.trace.outcome == "reject_batch_abort"
        for tk in good + [bad]:
            assert tk.state == "failed"
            with pytest.raises(SlateError):
                tk.result()
        # reservations were released on the error path too
        assert all(t["reserved_bytes"] == 0
                   for t in q.ledger.snapshot().values())
    finally:
        q.close()


# ---------------------------------------------------------------------------
# Router.max_n memo (satellite 1)
# ---------------------------------------------------------------------------


def test_max_n_memoized_across_router_instances():
    """The memory-model closed form evaluates ONCE per (op, nb, grid,
    dtype, budget) key process-wide: a steady-state stream of admission
    probes — across Router instances — hits the memo."""
    budget = 876_543_219  # unique: the memo is process-global
    before = _counts()["max_n_computes"]
    for _ in range(2):
        r = Router(bins=(BIN,), hbm_budget=budget, cache=ExecutableCache())
        for _ in range(50):
            r.admit("posv", N)
    assert _counts()["max_n_computes"] - before == 1


# ---------------------------------------------------------------------------
# the control loop
# ---------------------------------------------------------------------------


def test_hysteresis_trips_once_and_releases():
    h = Hysteresis(10.0, 2.0, arm=2, cooldown=2)
    assert h.observe(11) is None      # arming
    assert h.observe(12) == "trip"
    assert h.observe(15) is None      # latched: no repeated actuation
    assert h.observe(1) is None       # cooldown + arming
    assert h.observe(1) == "release"
    assert h.observe(1) is None       # already open


def test_hysteresis_no_flap_on_square_wave():
    h = Hysteresis(10.0, 2.0, arm=2, cooldown=1)
    edges = [h.observe(v) for v in [11, 1, 11, 1, 11, 1, 11, 1]]
    assert edges == [None] * 8        # streaks never arm


def test_controller_shrinks_window_on_latency_breach(rng, live_obs):
    """A seeded p95 spike on the PR 14 SLA surface trips the latency
    latch after ``arm`` ticks — one shrink_window actuation, recorded
    with its signals, and no flapping while the breach persists."""
    q, _clk = _make_queue("t_ctrl", max_batch=4, window_s=0.004)
    try:
        ctrl = ServiceController(q, slo_p95_s=0.25, arm=2, cooldown=2,
                                 failure_rate_hi=100.0,  # out of reach
                                 failure_rate_lo=0.0)
        for _ in range(20):
            REGISTRY.observe("serve.latency_s", 2.0, op="posv",
                             klass="friendly", outcome="served")
        for _ in range(6):
            ctrl.step()
        assert [a["action"] for a in ctrl.actuations] == ["shrink_window"]
        assert q.window_s == pytest.approx(0.002)
        assert q.max_batch == 4       # latency guard moves T, not B
        assert ctrl.actuations[0]["signals"]["p95_s"] >= 0.25
    finally:
        q.close()


def test_tier_map_moves_window_class(rng, live_obs):
    """The controller's precision-tier override changes the class every
    subsequent submit windows (and dispatches) under."""
    q, clk = _make_queue("t_tier", max_batch=8)
    try:
        a, b = _spd(rng), jnp.asarray(rng.standard_normal(N))
        assert q.router.effective_class("posv", a) == "friendly"
        q.router.tier_map = {"friendly": "hostile"}
        assert q.router.effective_class("posv", a) == "hostile"
        tk = q.submit("posv", a, b)
        with q._lock:
            (key,) = q._windows.keys()
        assert key[1] == "hostile"
        clk.advance(0.01)
        q.pump()
        assert tk.trace.outcome == "served"
        assert tk.trace.klass == "hostile"
    finally:
        q.close()


# ---------------------------------------------------------------------------
# the live scrape surface (satellite 2)
# ---------------------------------------------------------------------------


def test_queue_json_and_healthz(rng, live_obs):
    """obs.live serves ``/queue.json`` (every live queue's stats) and a
    queue-aware ``/healthz`` liveness line."""
    from slate_tpu.obs import live

    q, _clk = _make_queue("t_live", max_batch=8)
    srv = None
    try:
        q.submit("posv", _spd(rng), jnp.asarray(rng.standard_normal(N)),
                 tenant="acme")
        srv, _th, port = live.start_server(port=0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/queue.json", timeout=5) as r:
            doc = json.loads(r.read())
        stats = doc["queues"]["t_live"]
        assert stats["depth"] == 1
        assert stats["open_windows"] == 1
        assert stats["max_batch"] == 8
        assert stats["tenants"]["acme"]["reserved_bytes"] > 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
            body = r.read().decode()
        assert body.startswith("ok")
        assert "queues" in body and "depth" in body
    finally:
        if srv is not None:
            srv.shutdown()
        q.close()
