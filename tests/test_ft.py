"""ABFT subsystem tests (ISSUE 4): checksum-carrying kernels on the
8-device CPU mesh — FT off bitwise-identical, clean detect runs quiet
across dtypes, injected single-tile faults at every phase detected /
located / repaired within the op's tolerance, double faults escalating
to the structured FtError, and the policy/option/counter plumbing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.ft import FtError, FtPolicy, Fault, FaultPlan, fault_scope
from slate_tpu.ft import abft, checksum as cks, inject
from slate_tpu.ft.policy import ft_counter_values
from slate_tpu.parallel import (
    gemm_mesh,
    getrf_nopiv_mesh,
    make_mesh,
    posv_mesh,
    potrf_mesh,
    to_dense,
)
from slate_tpu.types import Option

from conftest import cpu_devices

N, NB = 64, 8
NT = N // NB
GRID = (2, 4)


def mesh24():
    return make_mesh(*GRID, devices=cpu_devices(8))


def _rand(rng, m, n, dtype=np.float64):
    return jnp.asarray(rng.standard_normal((m, n)).astype(dtype))


def _spd(rng, n, dtype=np.float64):
    g = rng.standard_normal((n, n))
    return jnp.asarray((g @ g.T + n * np.eye(n)).astype(dtype))


def _ddom(rng, n, dtype=np.float64):
    return jnp.asarray(
        (rng.standard_normal((n, n)) + n * np.eye(n)).astype(dtype)
    )


# ---------------------------------------------------------------------------
# (a) FT off reproduces the plain kernels bitwise
# ---------------------------------------------------------------------------


def test_ft_off_bitwise_identical(rng):
    mesh = mesh24()
    a, b = _rand(rng, N, N), _rand(rng, N, N)
    plain = gemm_mesh(1.0, a, b, mesh, nb=NB)
    for off in ("off", FtPolicy.Off):
        routed = gemm_mesh(1.0, a, b, mesh, nb=NB,
                           opts={Option.FaultTolerance: off})
        np.testing.assert_array_equal(np.asarray(plain), np.asarray(routed))
    spd = _spd(rng, N)
    l0, i0 = potrf_mesh(spd, mesh, nb=NB)
    l1, i1 = potrf_mesh(spd, mesh, nb=NB, opts={Option.FaultTolerance: "off"})
    np.testing.assert_array_equal(np.asarray(l0.tiles), np.asarray(l1.tiles))
    assert int(i0) == int(i1)


def test_bad_policy_rejected(rng):
    mesh = mesh24()
    a = _rand(rng, N, N)
    with pytest.raises(ValueError):
        gemm_mesh(1.0, a, a, mesh, nb=NB,
                  opts={Option.FaultTolerance: "warp-speed"})


# ---------------------------------------------------------------------------
# checksum algebra unit tests (no mesh)
# ---------------------------------------------------------------------------


def test_checksum_encode_locate_roundtrip(rng):
    nb, mt, nt = 4, 6, 5
    a = jnp.asarray(rng.standard_normal((mt * nb, nt * nb)))
    cs = cks.row_checksums(a, nb)
    # corrupt one tile, recompute, locate by the ramp/unit ratio
    bad = np.asarray(a).copy()
    ti, tj = 3, 2
    bad[ti * nb : (ti + 1) * nb, tj * nb : (tj + 1) * nb] *= 2.0
    d = np.asarray(cs - cks.row_checksums(jnp.asarray(bad), nb))
    d1, d2 = np.abs(d).reshape(2, nb, nt, nb).max(axis=(1, 3))
    assert np.argmax(d1) == tj and np.count_nonzero(d1 > 1e-12) == 1
    loc = cks.ratio_locate(
        d[:nb, tj * nb : (tj + 1) * nb], d[nb:, tj * nb : (tj + 1) * nb], mt
    )
    assert loc == ti
    # the unit discrepancy added back restores the tile exactly
    bad[ti * nb : (ti + 1) * nb, tj * nb : (tj + 1) * nb] += d[
        :nb, tj * nb : (tj + 1) * nb
    ]
    np.testing.assert_allclose(bad, np.asarray(a), atol=0)


def test_checksum_nonfinite_flags():
    d = np.zeros(6)
    d[2] = np.nan
    d[4] = np.inf
    assert list(cks.flag_mismatches(d, tol=1.0)) == [2, 4]
    assert cks.ratio_locate(np.full((2, 2), np.nan), np.ones((2, 2)), 4) == -1


# ---------------------------------------------------------------------------
# (b) detect with no fault: numerically clean, flags nothing, f32 + f64
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [np.float64, np.float32])
def test_detect_clean(rng, dtype):
    mesh = mesh24()
    tol = 1e-12 if dtype == np.float64 else 1e-4
    before = ft_counter_values()["detected"]
    a, b = _rand(rng, N, N, dtype), _rand(rng, N, N, dtype)
    c, rep = abft.gemm_ft(1.0, a, b, mesh, NB, policy=FtPolicy.Detect)
    ref = np.asarray(a) @ np.asarray(b)
    assert rep.clean
    assert np.abs(np.asarray(c) - ref).max() / np.abs(ref).max() < tol
    spd = _spd(rng, N, dtype)
    l, info, rep = abft.potrf_ft(spd, mesh, NB, policy=FtPolicy.Detect)
    ld = np.tril(np.asarray(to_dense(l)))
    assert rep.clean and int(info) == 0
    assert (np.abs(ld @ ld.T - np.asarray(spd)).max()
            / np.abs(np.asarray(spd)).max() < tol * 10)
    dd = _ddom(rng, N, dtype)
    lu, info, rep = abft.getrf_nopiv_ft(dd, mesh, NB, policy=FtPolicy.Detect)
    lud = np.asarray(to_dense(lu))
    resid = (np.tril(lud, -1) + np.eye(N, dtype=dtype)) @ np.triu(lud) - np.asarray(dd)
    assert rep.clean and int(info) == 0
    assert np.abs(resid).max() / np.abs(np.asarray(dd)).max() < tol * 10
    assert ft_counter_values()["detected"] == before  # nothing flagged


# ---------------------------------------------------------------------------
# (c) injected single-tile faults per phase: detect + locate + repair
# ---------------------------------------------------------------------------


def test_gemm_fault_all_phases(rng):
    mesh = mesh24()
    a, b = _rand(rng, N, N), _rand(rng, N, N)
    ref = np.asarray(a) @ np.asarray(b)
    for seed, phase in [(21, "trailing"), (22, "bcast"), (23, "trailing")]:
        f = inject.seeded_fault(seed, "gemm", NT, GRID, phase=phase)
        with fault_scope(FaultPlan([f])):
            c, rep = abft.gemm_ft(1.0, a, b, mesh, NB, policy=FtPolicy.Correct)
        assert rep.action in ("corrected", "recomputed"), (phase, rep.action)
        assert rep.detections, phase
        # located damage names the injected tile row or column
        wheres = [d["where"] for d in rep.detections]
        assert any(f.ti in w or f.tj in w for w in wheres), (f, wheres)
        err = np.abs(np.asarray(c) - ref).max() / np.abs(ref).max()
        assert err < 1e-12, (phase, err)
    # a single-tile trailing fault repairs algebraically, not by rerun
    f = inject.seeded_fault(21, "gemm", NT, GRID, phase="trailing")
    with fault_scope(FaultPlan([f])):
        _, rep = abft.gemm_ft(1.0, a, b, mesh, NB, policy=FtPolicy.Correct)
    assert rep.action == "corrected"


def test_potrf_fault_all_phases(rng):
    mesh = mesh24()
    spd = _spd(rng, N)
    expect = {"panel": "corrected", "bcast": "recomputed", "trailing": "recomputed"}
    for seed, phase in [(31, "panel"), (32, "bcast"), (33, "trailing")]:
        f = inject.seeded_fault(seed, "potrf", NT, GRID, phase=phase)
        with fault_scope(FaultPlan([f])):
            l, info, rep = abft.potrf_ft(spd, mesh, NB, policy=FtPolicy.Correct)
        assert rep.action == expect[phase], (phase, rep.action)
        assert int(info) == 0
        ld = np.tril(np.asarray(to_dense(l)))
        resid = (np.abs(ld @ ld.T - np.asarray(spd)).max()
                 / np.abs(np.asarray(spd)).max())
        assert resid < 1e-12, (phase, resid)


def test_lu_fault_all_phases(rng):
    mesh = mesh24()
    dd = _ddom(rng, N)
    expect = {"panel": "corrected", "bcast": "recomputed", "trailing": "recomputed"}
    for seed, phase in [(41, "panel"), (42, "bcast"), (43, "trailing")]:
        f = inject.seeded_fault(seed, "getrf_nopiv", NT, GRID, phase=phase)
        with fault_scope(FaultPlan([f])):
            lu, info, rep = abft.getrf_nopiv_ft(dd, mesh, NB, policy=FtPolicy.Correct)
        assert rep.action == expect[phase], (phase, rep.action)
        assert int(info) == 0
        lud = np.asarray(to_dense(lu))
        resid = (np.tril(lud, -1) + np.eye(N)) @ np.triu(lud) - np.asarray(dd)
        rel = np.abs(resid).max() / np.abs(np.asarray(dd)).max()
        assert rel < 1e-10, (phase, rel)


def test_detect_policy_failstops(rng):
    mesh = mesh24()
    spd = _spd(rng, N)
    f = inject.seeded_fault(51, "potrf", NT, GRID, phase="panel")
    with fault_scope(FaultPlan([f])):
        with pytest.raises(FtError) as ei:
            abft.potrf_ft(spd, mesh, NB, policy=FtPolicy.Detect)
    assert ei.value.op == "potrf" and ei.value.detections


def test_recompute_policy_skips_algebra(rng):
    # even the exactly-correctable panel fault reruns under `recompute`
    mesh = mesh24()
    spd = _spd(rng, N)
    f = inject.seeded_fault(52, "potrf", NT, GRID, phase="panel")
    with fault_scope(FaultPlan([f])):
        l, info, rep = abft.potrf_ft(spd, mesh, NB, policy=FtPolicy.Recompute)
    assert rep.action == "recomputed" and int(info) == 0
    ld = np.tril(np.asarray(to_dense(l)))
    assert (np.abs(ld @ ld.T - np.asarray(spd)).max()
            / np.abs(np.asarray(spd)).max() < 1e-12)


# ---------------------------------------------------------------------------
# (d) double fault -> FtError
# ---------------------------------------------------------------------------


def test_double_fault_raises_fterror(rng):
    mesh = mesh24()
    spd = _spd(rng, N)
    faults = [
        inject.seeded_fault(61, "potrf", NT, GRID, phase="trailing", persist=True),
        inject.seeded_fault(62, "potrf", NT, GRID, phase="trailing", persist=True),
    ]
    before = ft_counter_values()["uncorrectable"]
    with fault_scope(FaultPlan(faults)):
        with pytest.raises(FtError) as ei:
            abft.potrf_ft(spd, mesh, NB, policy=FtPolicy.Correct)
    assert "recompute" in str(ei.value)
    assert ft_counter_values()["uncorrectable"] > before
    # transient (one-shot) double fault: the recompute rerun is clean
    faults = [
        inject.seeded_fault(61, "potrf", NT, GRID, phase="trailing"),
        inject.seeded_fault(62, "potrf", NT, GRID, phase="trailing"),
    ]
    with fault_scope(FaultPlan(faults)):
        l, info, rep = abft.potrf_ft(spd, mesh, NB, policy=FtPolicy.Correct)
    assert rep.action == "recomputed" and int(info) == 0


# ---------------------------------------------------------------------------
# plumbing: drivers opts routing, api facade, counters/RunReport, lookahead
# ---------------------------------------------------------------------------


def test_driver_opts_routing_corrects(rng):
    mesh = mesh24()
    a, b = _rand(rng, N, N), _rand(rng, N, N)
    ref = np.asarray(a) @ np.asarray(b)
    f = inject.seeded_fault(71, "gemm", NT, GRID, phase="trailing")
    with fault_scope(FaultPlan([f])):
        c = gemm_mesh(1.0, a, b, mesh, nb=NB,
                      opts={Option.FaultTolerance: "correct"})
    assert np.abs(np.asarray(c) - ref).max() / np.abs(ref).max() < 1e-12
    # factor routing: potrf under FT solves an SPD system end to end
    spd = _spd(rng, N)
    xt = _rand(rng, N, 3)
    bb = jnp.asarray(np.asarray(spd) @ np.asarray(xt))
    x, info = posv_mesh(spd, bb, mesh, nb=NB,
                        opts={Option.FaultTolerance: FtPolicy.Correct})
    assert int(info) == 0
    assert np.abs(np.asarray(x) - np.asarray(xt)).max() < 1e-9
    lu, info = getrf_nopiv_mesh(_ddom(rng, N), mesh, nb=NB,
                                opts={Option.FaultTolerance: "detect"})
    assert int(info) == 0


def test_api_multiply_ft(rng):
    from slate_tpu import api

    a, b = _rand(rng, 48, 40), _rand(rng, 40, 24)
    ref = np.asarray(a) @ np.asarray(b)
    for pol in ("detect", "correct"):
        out = api.multiply(1.0, a, b, opts={Option.FaultTolerance: pol})
        assert np.abs(np.asarray(out) - ref).max() < 1e-12
    with pytest.raises(ValueError):
        api.multiply(1.0, a, b, opts={Option.FaultTolerance: "sometimes"})


def test_ft_counters_reach_runreport(rng):
    from slate_tpu.obs import report

    mesh = mesh24()
    a, b = _rand(rng, N, N), _rand(rng, N, N)
    before = ft_counter_values()
    f = inject.seeded_fault(81, "gemm", NT, GRID, phase="trailing")
    with fault_scope(FaultPlan([f])):
        abft.gemm_ft(1.0, a, b, mesh, NB, policy=FtPolicy.Correct)
    after = ft_counter_values()
    assert after["detected"] > before["detected"]
    assert after["corrected"] > before["corrected"]
    rep = report.make_report("ft_test")
    assert report.validate_report(rep) == []
    assert rep["ft"]["detected"] == after["detected"]
    # ft values join the --check comparison surface
    vals = report.load_values(rep)
    assert vals["ft_detected"] == after["detected"]


def test_ft_gemm_lookahead_depth_invariant(rng):
    # the checksum panels ride prefetch_bcast: any depth is bitwise-equal
    mesh = mesh24()
    a, b = _rand(rng, N, N), _rand(rng, N, N)
    outs = []
    for la in (0, 2):
        c, rep = abft.gemm_ft(1.0, a, b, mesh, NB,
                              policy=FtPolicy.Detect, lookahead=la)
        assert rep.clean
        outs.append(np.asarray(c))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_non_spd_keeps_info_semantics(rng):
    # verify-drive finding: a legitimately non-SPD input NaN-poisons the
    # factor (info != 0) — the FT layer must return the plain driver's
    # info contract, not misread the poison as corruption and FtError
    mesh = mesh24()
    bad = jnp.asarray(-np.eye(32))
    before = ft_counter_values()["uncorrectable"]
    l, info, rep = abft.potrf_ft(bad, mesh, 8, policy=FtPolicy.Correct)
    assert int(info) != 0
    assert rep.action == "clean"  # honest numerics, no fault claimed
    assert ft_counter_values()["uncorrectable"] == before
    # and under detect too: breakdown is not a detection
    l, info, rep = abft.potrf_ft(bad, mesh, 8, policy=FtPolicy.Detect)
    assert int(info) != 0


# ---------------------------------------------------------------------------
# (h) trsm ABFT (ISSUE 12 satellite): the solution-checksum carrier
# ---------------------------------------------------------------------------


def test_trsm_abft_detect_correct_recompute(rng):
    """The checksum columns ride the RHS through the unchanged TrsmB
    schedule: clean runs are quiet; a corrupted ALREADY-SOLVED X tile
    (final data) repairs exactly from the unit discrepancy; a corrupted
    not-yet-solved tile propagates and escalates to one recompute; the
    detect policy fail-stops."""
    mesh = mesh24()
    tl = jnp.asarray(np.tril(np.asarray(_rand(rng, N, N))) + N * np.eye(N))
    b = _rand(rng, N, 2 * NB)
    ref = np.linalg.solve(np.asarray(tl), np.asarray(b))

    def err(x):
        return np.abs(np.asarray(x) - ref).max() / np.abs(ref).max()

    x, rep = abft.trsm_ft(tl, b, mesh, NB, policy=FtPolicy.Correct)
    assert rep.clean and err(x) < 1e-10

    final = Fault("trsm", k=NT - 1, phase="trailing", ti=1, tj=0,
                  r=1 % GRID[0], c=0, mode=inject.MODE_SCALE, value=3.0)
    with fault_scope(FaultPlan([final])):
        x2, rep2 = abft.trsm_ft(tl, b, mesh, NB, policy=FtPolicy.Correct)
    assert rep2.action == "corrected" and err(x2) < 1e-10

    live = Fault("trsm", k=1, phase="trailing", ti=5, tj=1,
                 r=5 % GRID[0], c=1 % GRID[1], mode=inject.MODE_SCALE,
                 value=3.0)
    with fault_scope(FaultPlan([live])):
        x3, rep3 = abft.trsm_ft(tl, b, mesh, NB, policy=FtPolicy.Correct)
    assert rep3.action == "recomputed" and err(x3) < 1e-10

    with fault_scope(FaultPlan([Fault(
        "trsm", k=NT - 1, phase="trailing", ti=2, tj=0, r=0, c=0,
        mode=inject.MODE_SCALE, value=2.0,
    )])):
        with pytest.raises(FtError):
            abft.trsm_ft(tl, b, mesh, NB, policy=FtPolicy.Detect)


def test_her2k_abft_off_bitwise_and_clean(rng):
    """her2k_ft (ISSUE 13): policy Off is bitwise the plain full her2k;
    a clean protected run is quiet and matches the dense reference."""
    from slate_tpu.parallel import from_dense
    from slate_tpu.parallel.dist_blas3 import her2k_dist

    mesh = mesh24()
    a, b = _rand(rng, N, N), _rand(rng, N, N)
    off, rep0 = abft.her2k_ft(1.0, a, b, mesh, NB, policy=FtPolicy.Off)
    plain = to_dense(her2k_dist(
        1.0, from_dense(a, mesh, NB), from_dense(b, mesh, NB), full=True
    ))[:N, :N]
    assert rep0.clean
    np.testing.assert_array_equal(np.asarray(off), np.asarray(plain))

    c, rep = abft.her2k_ft(1.0, a, b, mesh, NB, policy=FtPolicy.Detect)
    ref = np.asarray(a) @ np.asarray(b).T + np.asarray(b) @ np.asarray(a).T
    assert rep.clean
    assert np.abs(np.asarray(c) - ref).max() / np.abs(ref).max() < 1e-12


def test_her2k_abft_inject_detect_repair(rng):
    """Injected accumulator damage is final data — exactly correctable
    from the carried checksums (the GEMM repair class); a received-panel
    (bcast) fault lands clean through repair-or-recompute; the detect
    policy fail-stops; counters move."""
    mesh = mesh24()
    a, b = _rand(rng, N, N), _rand(rng, N, N)
    ref = np.asarray(a) @ np.asarray(b).T + np.asarray(b) @ np.asarray(a).T

    def err(x):
        return np.abs(np.asarray(x) - ref).max() / np.abs(ref).max()

    before = ft_counter_values()
    trail = Fault("her2k", k=NT - 1, phase="trailing", ti=3, tj=1,
                  r=3 % GRID[0], c=1 % GRID[1], mode=inject.MODE_SCALE,
                  value=3.0)
    with fault_scope(FaultPlan([trail])):
        c1, rep1 = abft.her2k_ft(1.0, a, b, mesh, NB,
                                 policy=FtPolicy.Correct)
    assert rep1.action == "corrected" and err(c1) < 1e-12

    bc = Fault("her2k", k=2, phase="bcast", ti=4, tj=2, r=4 % GRID[0],
               c=1, mode=inject.MODE_SCALE, value=3.0)
    with fault_scope(FaultPlan([bc])):
        c2, rep2 = abft.her2k_ft(1.0, a, b, mesh, NB,
                                 policy=FtPolicy.Correct)
    assert rep2.action in ("corrected", "recomputed") and err(c2) < 1e-12

    with fault_scope(FaultPlan([Fault(
        "her2k", k=1, phase="trailing", ti=5, tj=2, r=5 % GRID[0],
        c=2 % GRID[1], mode=inject.MODE_SCALE, value=2.0,
    )])):
        with pytest.raises(FtError):
            abft.her2k_ft(1.0, a, b, mesh, NB, policy=FtPolicy.Detect)
    after = ft_counter_values()
    assert after["detected"] >= before["detected"] + 3
    assert after["corrected"] > before["corrected"]

    # beta C rides the augmented accumulator consistently (linearity)
    c0 = _spd(rng, N)
    cc, repc = abft.her2k_ft(1.0, a, b, mesh, NB, beta=0.5, c=c0,
                             policy=FtPolicy.Detect)
    refc = ref + 0.5 * np.asarray(c0)
    assert repc.clean
    assert np.abs(np.asarray(cc) - refc).max() / np.abs(refc).max() < 1e-12
