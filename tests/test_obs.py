"""Observability layer coverage (ISSUE 2): span nesting + tag
propagation, comm-counter accumulation under jit trace-once semantics,
Perfetto JSON schema validation, RunReport schema + ``--check``
pass/fail paths, the Trace.finish JSON fallback, and the measure()
wall/compile/execute split."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu import obs
from slate_tpu.obs import perfetto, report
from slate_tpu.parallel.comm import comm_audit, psum_a


@pytest.fixture
def fresh_obs():
    obs.reset()
    with obs.force_enabled():
        yield
    obs.reset()


def _mesh_and_spd(n=64, nb=8):
    from slate_tpu.parallel import from_dense, make_mesh

    mesh = make_mesh(2, 4, devices=jax.devices("cpu")[:8])
    rng = np.random.default_rng(0)
    g = rng.standard_normal((n, n))
    spd = jnp.asarray((g @ g.T / n + 2 * np.eye(n)).astype(np.float32))
    return mesh, from_dense(spd, mesh, nb, diag_pad_one=True)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_disabled_span_is_noop():
    obs.reset()
    assert not obs.enabled()
    before = len(obs.FINISHED)
    with obs.driver_span("nothing", n=4) as sp:
        sp.set("x", 1.0)  # must not touch the registry
    assert len(obs.FINISHED) == before
    assert obs.REGISTRY.counter_value("span_count", span="nothing") == 0.0


def test_span_nesting_and_tag_propagation(fresh_obs):
    with obs.driver_span("outer", n=32) as so:
        with obs.driver_span("inner", phase="x"):
            pass
    names = {s["name"]: s for s in obs.FINISHED}
    assert names["inner"]["parent"] == "outer"
    assert names["inner"]["depth"] == 1
    assert names["outer"]["parent"] is None
    assert names["outer"]["tags"] == {"n": "32"}
    assert names["inner"]["tags"] == {"phase": "x"}
    assert names["outer"]["metrics"]["wall_seconds"] >= \
        names["inner"]["metrics"]["wall_seconds"]
    assert so.metrics["wall_seconds"] > 0


def test_instrumented_driver_records_span_and_comm_bytes(fresh_obs):
    from slate_tpu.parallel import potrf_dist

    _, ad = _mesh_and_spd()
    jax.clear_caches()
    _, info = potrf_dist(ad)
    assert int(info) == 0
    spans = [s for s in obs.FINISHED if s["name"] == "potrf_dist"]
    assert len(spans) == 1
    # instrument() tags the span with the DistMatrix geometry
    assert spans[0]["tags"] == {"m": "64", "n": "64", "nb": "8"}
    assert spans[0]["metrics"]["comm_bytes"] > 0


def test_comm_counter_trace_once_semantics(fresh_obs):
    """The comm-byte counters record at jit trace time only: a warm call
    (cache hit) must add nothing — the documented comm_audit contract,
    now holding through the span absorption layer too.  The lowering is
    pinned to the legacy psum path so the per-op counter name under test
    is impl-independent (the engine default records ppermute ops)."""
    from slate_tpu.parallel import potrf_dist
    from slate_tpu.parallel.comm import use_bcast_impl

    _, ad = _mesh_and_spd()
    jax.clear_caches()
    with use_bcast_impl("psum"):
        potrf_dist(ad)
        first = obs.REGISTRY.counter_value(
            "comm_bytes", span="potrf_dist", op="psum")
        assert first > 0
        potrf_dist(ad)  # warm: no re-trace, no new bytes
        assert obs.REGISTRY.counter_value(
            "comm_bytes", span="potrf_dist", op="psum") == first
    warm = [s for s in obs.FINISHED if s["name"] == "potrf_dist"][-1]
    assert warm["metrics"]["comm_bytes"] == 0.0
    # span_count keeps counting executions even when bytes don't re-record
    assert obs.REGISTRY.counter_value("span_count", span="potrf_dist") == 2.0


def test_span_propagates_records_to_outer_audit(fresh_obs):
    """A span inside comm_audit() must observe without stealing: the
    outer audit (slate_lint's trace pass, tools/comm_audit.py) still sees
    every record."""
    fn = jax.vmap(lambda x: psum_a(x, "i"), axis_name="i")
    with comm_audit() as outer:
        with obs.driver_span("probe"):
            jax.make_jaxpr(fn)(jnp.zeros((4, 8)))
    assert len(outer) == 1
    assert outer[0][0] == "psum[i]"
    probe = [s for s in obs.FINISHED if s["name"] == "probe"][0]
    assert probe["metrics"]["comm_bytes"] == outer[0][1]


def test_timer_blocks_feed_metrics(fresh_obs):
    from slate_tpu.utils import trace

    with trace.block("phase_x"):
        pass
    assert obs.REGISTRY.counter_value("timer_seconds", timer="phase_x") > 0


# ---------------------------------------------------------------------------
# measure(): wall/compile/execute phases + cost analysis
# ---------------------------------------------------------------------------


def test_measure_splits_phases_and_pulls_cost():
    obs.reset()
    a = jnp.ones((64, 64), jnp.float32)
    out, m = obs.measure("toy_mm", jax.jit(lambda x: x @ x), a)
    np.testing.assert_allclose(np.asarray(out), np.asarray(a @ a))
    for key in ("wall_seconds", "compile_seconds", "execute_seconds",
                "comm_bytes"):
        assert key in m, key
    # one AOT lower+compile, one execution — wall covers both phases
    assert m["wall_seconds"] >= m["compile_seconds"] + m["execute_seconds"]
    # XLA's cost model knows a 64^3 matmul
    if "flops" in m:
        assert m["flops"] >= 2 * 64**3 * 0.5
    obs.reset()


# ---------------------------------------------------------------------------
# Perfetto export
# ---------------------------------------------------------------------------


def test_perfetto_export_schema_and_nesting(fresh_obs, tmp_path):
    with obs.driver_span("parent_op", n=16):
        with obs.driver_span("child_op"):
            pass
    path = perfetto.write_chrome_trace(str(tmp_path / "trace.json"),
                                       legacy_events=[("legacy", 2, 0.0, 0.5)])
    with open(path) as f:
        tr = json.load(f)
    assert perfetto.validate_chrome_trace(tr) == []
    evs = {e["name"]: e for e in tr["traceEvents"]}
    assert evs["child_op"]["args"]["parent"] == "parent_op"
    assert evs["parent_op"]["args"]["n"] == "16"
    assert evs["legacy"]["tid"] == 102 and evs["legacy"]["dur"] == 0.5e6
    for e in (evs["parent_op"], evs["child_op"]):
        assert e["ph"] == "X" and e["ts"] >= 0 and e["dur"] >= 0


def test_perfetto_validator_catches_garbage():
    assert perfetto.validate_chrome_trace([]) != []
    assert perfetto.validate_chrome_trace({"traceEvents": "nope"}) != []
    bad = {"traceEvents": [{"name": "", "ph": "X", "ts": -1}]}
    errs = perfetto.validate_chrome_trace(bad)
    assert any("name" in e for e in errs) and any("ts" in e for e in errs)


def test_trace_finish_json_fallback_without_native_writer(tmp_path, monkeypatch):
    """ISSUE 2 satellite: Trace.finish used to DROP all collected events
    when the native SVG writer was missing — now they survive as a
    Chrome-trace JSON, and are kept entirely when even that write fails."""
    from slate_tpu.utils.trace import Trace
    from slate_tpu.utils import trace as trace_mod

    monkeypatch.setattr(trace_mod, "_load_writer", lambda: None)
    Trace.on()
    Trace.add("ev_a", 0, 0.0, 1.0)
    Trace.add("ev_b", 1, 0.5, 2.0)
    # write failure (directory does not exist): events must be KEPT
    out = Trace.finish(str(tmp_path / "missing_dir" / "t.svg"))
    assert out is None
    assert len(Trace._events) == 2
    # fallback success: JSON written next to the requested path
    out = Trace.finish(str(tmp_path / "t.svg"))
    assert out == str(tmp_path / "t.svg.json")
    with open(out) as f:
        tr = json.load(f)
    assert perfetto.validate_chrome_trace(tr) == []
    assert {e["name"] for e in tr["traceEvents"]} >= {"ev_a", "ev_b"}
    assert Trace._events == []
    Trace.off()


# ---------------------------------------------------------------------------
# RunReport schema + --check
# ---------------------------------------------------------------------------


def test_report_roundtrip_validates(fresh_obs, tmp_path):
    with obs.driver_span("r_op"):
        pass
    path = report.write_report(str(tmp_path / "r.json"), name="unit",
                               config={"n": 8},
                               values={"x_gflops": 100.0, "t_seconds": 1.0})
    with open(path) as f:
        rep = json.load(f)
    assert report.validate_report(rep) == []
    assert rep["values"]["x_gflops"] == 100.0
    assert any(s["name"] == "r_op" for s in rep["spans"])
    # corruption is caught
    del rep["values"]
    assert report.validate_report(rep) != []
    assert report.validate_report("not a dict") != []


def test_check_flags_2x_regression_and_passes_unchanged():
    base = {"x_gflops": 100.0, "t_seconds": 1.0}
    # unchanged: clean
    fails, n = report.check_regression(dict(base), dict(base))
    assert fails == [] and n == 2
    # 2x worse in each direction: both flagged
    fails, _ = report.check_regression(
        {"x_gflops": 50.0, "t_seconds": 2.0}, base)
    assert len(fails) == 2
    # 2x BETTER in each direction: never flagged
    fails, _ = report.check_regression(
        {"x_gflops": 200.0, "t_seconds": 0.5}, base)
    assert fails == []
    # within threshold: clean
    fails, _ = report.check_regression(
        {"x_gflops": 80.0, "t_seconds": 1.2}, base)
    assert fails == []


def test_report_cli_check_exit_codes(tmp_path):
    old = str(tmp_path / "old.json")
    new_ok = str(tmp_path / "new_ok.json")
    new_bad = str(tmp_path / "new_bad.json")
    obs.reset()
    report.write_report(old, name="cli", values={"x_gflops": 100.0})
    report.write_report(new_ok, name="cli", values={"x_gflops": 95.0})
    report.write_report(new_bad, name="cli", values={"x_gflops": 40.0})
    assert report.main(["--check", new_ok, old]) == 0
    assert report.main(["--check", new_bad, old]) == 1
    assert report.main([old]) == 0  # pretty-print path
    # no shared metrics -> inconclusive exit 2
    other = str(tmp_path / "other.json")
    report.write_report(other, name="cli", values={"y_gflops": 1.0})
    assert report.main(["--check", other, old]) == 2


def test_report_reads_legacy_bench_and_sweep_shapes():
    bench_line = {"metric": "dgemm_gflops", "value": 4700.0, "unit": "GFLOP/s",
                  "extras": {"gemm_bf16_gflops": 100000.0, "note": "text"}}
    vals = report.load_values(bench_line)
    assert vals == {"dgemm_gflops": 4700.0, "gemm_bf16_gflops": 100000.0}
    sweep = {"results": [
        {"routine": "potrf_f64", "n": 16384, "gflops": 1234.0, "ok": True},
        {"routine": "heev", "n": 8192, "gflops": 99.0, "ok": False},
    ]}
    assert report.load_values(sweep) == {"potrf_f64_n16384_gflops": 1234.0}
    with pytest.raises(ValueError):
        report.load_values({"mystery": 1})


def test_report_unwraps_driver_bench_artifact():
    """The repo's real BENCH_*.json files are driver wrappers holding the
    bench stdout in "tail"; --check must gate against them directly."""
    wrapper = {"n": 4, "cmd": "python bench.py", "rc": 0,
               "tail": "noise\n[bench 1s] progress\n"
                       '{"metric": "dgemm_gflops", "value": 5196.0, '
                       '"extras": {"gemm_bf16_gflops": 150000.0}}\n'}
    vals = report.load_values(wrapper)
    assert vals == {"dgemm_gflops": 5196.0, "gemm_bf16_gflops": 150000.0}
    with pytest.raises(ValueError):  # timed-out run: no metric line
        report.load_values({"rc": 124, "tail": "killed before the line"})


def test_check_skips_tagged_flops_series_and_generator_spans(tmp_path):
    """Review regressions: (1) the _NEUTRAL exclusion must match the
    metric-name side of a flattened 'flops|span=...' series, so a dropped
    XLA flop estimate (an optimization) never fails --check; (2) the
    perfetto exporter must accept a generator of spans without silently
    emitting an empty trace."""
    fails, _ = report.check_regression(
        {"flops|span=dist_chol": 1e6, "x_gflops": 100.0},
        {"flops|span=dist_chol": 2.5e6, "x_gflops": 100.0},
    )
    assert fails == []
    spans = ({"name": f"s{i}", "tags": {}, "t0": float(i), "t1": i + 0.5,
              "depth": 0, "parent": None, "metrics": {}} for i in range(3))
    tr = perfetto.chrome_trace(spans=spans)
    assert perfetto.validate_chrome_trace(tr) == []
    assert {e["name"] for e in tr["traceEvents"]} >= {"s0", "s1", "s2"}


def test_check_defaults_to_headline_values_only(tmp_path):
    """--check gates the workload-keyed headline values by default; the
    run-scaled counter/histogram series join only with --all-metrics."""
    obs.reset()
    old = str(tmp_path / "old.json")
    new = str(tmp_path / "new.json")
    with obs.force_enabled():
        with obs.driver_span("short_op"):
            pass
    report.write_report(old, name="cfg", config={"dim": "256"},
                        values={"x_gflops": 100.0})
    obs.reset()
    with obs.force_enabled():  # a 4x-bigger sweep: 4 spans, same rate
        for _ in range(4):
            with obs.driver_span("short_op"):
                pass
    report.write_report(new, name="cfg", config={"dim": "256:1024:256"},
                        values={"x_gflops": 100.0})
    # default: the 4x-scaled span series do not even enter the gate.
    # The mem section (ISSUE 9) samples at enabled span exits and joins
    # the headline surface like ft/ir; everything ELSE stays out.
    assert report.main(["--check", new, old]) == 0
    vals_default = report.load_values(json.load(open(new)))
    assert {k for k in vals_default if not k.startswith("mem_")} \
        == {"x_gflops"}
    # opt-in exposes the run-scaled series (same-config pairs only)
    vals_all = report.load_values(json.load(open(new)), include_series=True)
    assert vals_all["span_count|span=short_op"] == 4.0
    assert set(vals_all) > set(vals_default)
    obs.reset()


def test_legacy_t0_aligns_mixed_timebases():
    spans = [{"name": "sp", "tags": {}, "t0": 100.0, "t1": 101.0,
              "depth": 0, "parent": None, "metrics": {}}]
    # legacy clock started at perf_counter()=99.5; its event at +1.0s is
    # absolute 100.5 = 0.5s after the span base in the merged trace
    tr = perfetto.chrome_trace(spans=spans,
                               legacy_events=[("lg", 0, 1.0, 1.25)],
                               legacy_t0=99.5)
    evs = {e["name"]: e for e in tr["traceEvents"]}
    assert evs["sp"]["ts"] == 0.0
    assert evs["lg"]["ts"] == pytest.approx(0.5e6)
    assert evs["lg"]["dur"] == pytest.approx(0.25e6)
    # without legacy_t0 the legacy track keeps its own zero (old behavior)
    tr2 = perfetto.chrome_trace(spans=spans, legacy_events=[("lg", 0, 1.0, 1.25)])
    assert {e["name"]: e for e in tr2["traceEvents"]}["lg"]["ts"] == pytest.approx(1.0e6)


def test_check_cli_inconclusive_on_unreadable_artifacts(tmp_path):
    """--check must exit 2 (inconclusive), not 1 (regression), on corrupt
    or timed-out prior artifacts — exit 1 is reserved for real
    regressions."""
    obs.reset()
    good = str(tmp_path / "good.json")
    report.write_report(good, name="cli", values={"x_gflops": 100.0})
    timed_out = str(tmp_path / "bench_timeout.json")
    with open(timed_out, "w") as f:
        json.dump({"rc": 124, "tail": "killed before the metric line"}, f)
    assert report.main(["--check", good, timed_out]) == 2
    garbage = str(tmp_path / "garbage.json")
    with open(garbage, "w") as f:
        f.write("{not json")
    assert report.main(["--check", good, garbage]) == 2
    assert report.main(["--check", good, str(tmp_path / "missing.json")]) == 2


def test_check_regression_flags_zero_collapse():
    # round-4 (ft PR) review finding: a higher-is-better metric hitting
    # exactly zero must gate as a regression, not skip as an undefined
    # ratio (ft_detected 5 -> 0 = detection coverage silently lost)
    from slate_tpu.obs.report import check_regression

    fails, n = check_regression(
        {"x_gflops": 0.0, "ft_detected": 0.0},
        {"x_gflops": 5.0, "ft_detected": 5.0},
    )
    assert n == 2 and len(fails) == 2
    # lower-is-better hitting zero is an improvement, not a failure
    fails, n = check_regression({"wall_seconds": 0.0}, {"wall_seconds": 5.0})
    assert fails == []


def test_check_mixed_schema_sections_inconclusive(tmp_path, capsys):
    """ISSUE 7 satellite: when the NEW report carries a metrics section
    the OLD artifact predates (sched.* from a flight report, ft_*
    against a pre-ft report), --check reports those keys as
    per-key INCONCLUSIVE instead of failing the whole check — the shared
    metrics still gate normally."""
    # unit surface: the section filter
    assert report.inconclusive_keys(
        {"wall_seconds": 1.0, "sched.overlap_eff": 0.5, "ft_detected": 2.0,
         "new_gflops": 9.0},
        {"wall_seconds": 1.0},
    ) == ["ft_detected", "sched.overlap_eff"]  # new_gflops: not a section
    # shared key present in both: never inconclusive
    assert report.inconclusive_keys(
        {"sched.overlap_eff": 0.5}, {"sched.overlap_eff": 0.4}) == []

    # CLI surface: mixed-schema pair passes (rc 0) with INCONCLUSIVE lines
    old = str(tmp_path / "old.json")
    new = str(tmp_path / "new.json")
    obs.reset()
    report.write_report(old, name="mixed", values={"x_gflops": 100.0})
    report.write_report(new, name="mixed",
                        values={"x_gflops": 101.0,
                                "sched.overlap_eff": 0.6,
                                "sched.critical_path_s": 0.02})
    assert report.main(["--check", new, old]) == 0
    out = capsys.readouterr().out
    assert out.count("INCONCLUSIVE") == 2
    assert "sched.overlap_eff" in out and "sched.critical_path_s" in out
    obs.reset()


def test_histogram_quantiles_exact_reservoir_and_snapshot():
    """ISSUE 14 satellite: first-class histogram quantiles — exact
    (interpolated over every observation) below the reservoir cap with
    running-stats clamping, a deterministic reservoir estimate beyond
    it, and p50/p95/p99 surfaced in snapshots."""
    from slate_tpu.obs.metrics import (
        _HIST_SAMPLE_CAP,
        MetricsRegistry,
        quantile_of,
    )

    reg = MetricsRegistry()
    # tiny counts: 1 observation returns it, 2 interpolate exactly
    reg.observe("lat", 3.0, op="tiny")
    assert reg.quantile("lat", 0.0, op="tiny") == 3.0
    assert reg.quantile("lat", 0.99, op="tiny") == 3.0
    reg.observe("lat", 5.0, op="tiny")
    assert reg.quantile("lat", 0.5, op="tiny") == 4.0
    # exact tier: 1..10 -> interpolated median 5.5, extremes exact
    for v in range(1, 11):
        reg.observe("lat", float(v), op="x")
    assert reg.quantile("lat", 0.5, op="x") == 5.5
    assert reg.quantile("lat", 0.0, op="x") == 1.0
    assert reg.quantile("lat", 1.0, op="x") == 10.0
    # an unobserved series has no quantiles
    assert reg.quantile("lat", 0.5, op="nope") is None
    with pytest.raises(ValueError):
        quantile_of([1.0], 1.5)
    # beyond the cap: reservoir estimate stays within the exact running
    # extrema, monotone across q, with deterministic samples
    nbig = 4 * _HIST_SAMPLE_CAP
    for v in range(nbig):
        reg.observe("lat", float(v), op="big")
    p50 = reg.quantile("lat", 0.5, op="big")
    p95 = reg.quantile("lat", 0.95, op="big")
    p99 = reg.quantile("lat", 0.99, op="big")
    assert 0.0 <= p50 <= p95 <= p99 <= nbig - 1
    assert abs(p50 - nbig / 2) < nbig * 0.15  # loose reservoir sanity
    reg2 = MetricsRegistry()
    for v in range(nbig):
        reg2.observe("lat", float(v), op="big")
    assert reg2.quantile("lat", 0.99, op="big") == p99  # deterministic
    # snapshot carries the quantile surface per series
    hsnap = {(e["name"], str(sorted(e["tags"].items()))): e
             for e in reg.snapshot()["histograms"]}
    entry = hsnap[("lat", str(sorted({"op": "x"}.items())))]
    assert entry["count"] == 10 and entry["p50"] == 5.5
    assert entry["p99"] <= entry["max"] == 10.0
