"""SVD tests: ge2tb band structure, tb2bd, bdsqr, and the full driver —
mirrors reference test_svd.cc / test_ge2tb.cc / test_tb2bd.cc."""

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.linalg.svd import bdsqr, ge2tb, svd_array, tb2bd
from slate_tpu.utils.testing import generate


def test_bdsqr():
    n = 20
    rng = np.random.default_rng(1)
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    B = np.diag(d) + np.diag(e, 1)
    s, u, v = bdsqr(jnp.asarray(d), jnp.asarray(e))
    s, u, v = map(np.asarray, (s, u, v))
    sref = np.linalg.svd(B, compute_uv=False)
    assert np.abs(s - sref).max() < 1e-12
    assert np.abs(B @ v - u * s).max() < 1e-12
    # GK-embedding caveat: u/v orthogonality degrades as eps/sigma_min for
    # tiny singular values (the +/-sigma eigenpairs nearly collide); residual
    # and values stay at machine precision (svd.bdsqr docstring)
    assert np.abs(u.T @ u - np.eye(n)).max() < 1e-8


def test_ge2tb_band():
    m, n, nb = 48, 32, 8
    a = np.asarray(generate("rands", m, n, np.float64, seed=2))
    f = ge2tb(jnp.asarray(a), nb)
    band = np.asarray(f.band)
    assert np.abs(np.tril(band, -1)).max() == 0
    assert np.abs(np.triu(band, nb + 1)).max() < 1e-13
    serr = np.abs(
        np.linalg.svd(band, compute_uv=False) - np.linalg.svd(a, compute_uv=False)
    ).max()
    assert serr < 1e-12 * m


def test_tb2bd():
    n, nb = 32, 8
    a = np.asarray(generate("rands", n, n, np.float64, seed=3))
    band = np.asarray(ge2tb(jnp.asarray(a), nb).band)
    d, e, f, pu, pv = tb2bd(jnp.asarray(band), nb)
    B = np.diag(np.asarray(d)) + np.diag(np.asarray(e), 1)
    serr = np.abs(
        np.linalg.svd(B, compute_uv=False) - np.linalg.svd(band, compute_uv=False)
    ).max()
    assert serr < 1e-12 * n


@pytest.mark.parametrize("shape", [(40, 28), (32, 32), (25, 40)])
@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_svd_full(shape, dtype):
    m, n = shape
    a = np.asarray(generate("randn", m, n, dtype, seed=4))
    u, s, vh = svd_array(jnp.asarray(a), nb=8)
    u, s, vh = map(np.asarray, (u, s, vh))
    k = min(m, n)
    sref = np.linalg.svd(a, compute_uv=False)
    assert np.abs(s - sref).max() < 1e-12 * max(m, n)
    assert np.abs(a - (u * s) @ vh).max() < 1e-12 * max(m, n)
    assert np.abs(u.conj().T @ u - np.eye(k)).max() < 1e-12 * max(m, n)
    assert np.abs(vh @ vh.conj().T - np.eye(k)).max() < 1e-12 * max(m, n)


def test_svd_values_only():
    a = np.asarray(generate("rands", 30, 20, np.float64, seed=5))
    s = np.asarray(svd_array(jnp.asarray(a), want_vectors=False, nb=8))
    assert np.abs(s - np.linalg.svd(a, compute_uv=False)).max() < 1e-11


def test_svd_staged_matches_fused():
    from slate_tpu.linalg.svd import svd_staged

    rng = np.random.default_rng(21)
    for m, n in [(80, 64), (40, 70)]:  # tall + the m<n transpose branch
        a = rng.standard_normal((m, n))
        u, s, vh = svd_staged(jnp.asarray(a), nb=16)
        un, sn, vn = np.asarray(u), np.asarray(s), np.asarray(vh)
        sref = np.linalg.svd(a, compute_uv=False)
        k = min(m, n)
        assert np.abs(sn - sref).max() < 1e-12 * k * max(1, sref.max())
        assert np.abs(a - (un * sn) @ vn).max() < 1e-12 * k * max(1, sref.max())
        assert np.abs(un.T @ un - np.eye(un.shape[1])).max() < 1e-12 * k
        sv = np.asarray(svd_staged(jnp.asarray(a), want_vectors=False, nb=16))
        assert np.abs(sv - sref).max() < 1e-11 * k


def test_ge2tb_segmented_matches_fused():
    # the segmented ge2tb dispatch (svd_staged's chip path past the chase
    # segmentation threshold) must match the fused loop exactly
    from slate_tpu.linalg.svd import ge2tb

    rng = np.random.default_rng(9)
    a = jnp.asarray(rng.standard_normal((96, 64)))
    f1 = ge2tb(a, 16)
    f2 = ge2tb(a, 16, segments=3)
    assert np.abs(np.asarray(f1.band) - np.asarray(f2.band)).max() == 0.0
    assert np.abs(np.asarray(f1.vq) - np.asarray(f2.vq)).max() == 0.0
    assert np.abs(np.asarray(f1.tl) - np.asarray(f2.tl)).max() == 0.0
