"""Ozaki split-int8 f64 GEMM (slate_tpu/ops/ozaki.py) — accuracy gates
against numpy f64, including mixed row magnitudes, k-chunking, and the
digit-boundary adversarial case."""

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.ops.ozaki import matmul_f64


@pytest.mark.parametrize("shape", [(64, 64, 64), (128, 300, 65), (96, 8192, 64)])
@pytest.mark.parametrize("scale", [1.0, 1e8, 1e-12])
def test_matmul_f64_accuracy(shape, scale):
    m, k, n = shape
    rng = np.random.default_rng(0)
    a = rng.standard_normal((m, k)) * scale
    a[::3] *= 1e6  # mixed row magnitudes exercise the per-row exponents
    b = rng.standard_normal((k, n))
    c = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
    ref = a @ b
    rel = np.abs(c - ref).max() / np.abs(ref).max()
    assert rel < 1e-13, rel


def test_matmul_f64_adversarial_boundaries():
    # every element just below a power of two: all digit planes saturate
    a = np.full((64, 8192), 0.9999999999)
    b = np.full((8192, 64), -0.9999999999)
    c = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
    ref = a @ b
    rel = np.abs(c - ref).max() / np.abs(ref).max()
    assert rel < 1e-13, rel


def test_matmul_f64_zero_rows_and_fast_variant():
    rng = np.random.default_rng(1)
    a = rng.standard_normal((32, 50))
    a[5] = 0.0  # zero row: exponent guard
    b = rng.standard_normal((50, 32))
    c = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
    assert np.abs(c - a @ b).max() / np.abs(a @ b).max() < 1e-13
    # reduced-slice variant trades accuracy for speed but stays ~f32-pair
    c6 = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b), n_slices=6))
    assert np.abs(c6 - a @ b).max() / np.abs(a @ b).max() < 1e-8


def test_matmul_f64_rejects_f32():
    with pytest.raises(TypeError):
        matmul_f64(jnp.ones((4, 4), jnp.float32), jnp.ones((4, 4), jnp.float32))


def test_matmul_c128_karatsuba():
    from slate_tpu.ops.ozaki import matmul_c128

    rng = np.random.default_rng(2)
    a = rng.standard_normal((48, 96)) + 1j * rng.standard_normal((48, 96))
    b = rng.standard_normal((96, 32)) + 1j * rng.standard_normal((96, 32))
    c = np.asarray(matmul_c128(jnp.asarray(a), jnp.asarray(b)))
    ref = a @ b
    assert np.abs(c - ref).max() / np.abs(ref).max() < 1e-13


def test_matmul_dispatch_precision_tiers(monkeypatch):
    """matmul() routes f64/c128 through the Ozaki path when the default
    device is a TPU, and through jnp.matmul otherwise; tiers map to XLA
    precisions for f32."""
    import importlib

    mm = importlib.import_module("slate_tpu.ops.matmul")
    from slate_tpu.types import Precision

    rng = np.random.default_rng(3)
    a = rng.standard_normal((32, 40))
    b = rng.standard_normal((40, 24))
    ref = a @ b
    # CPU default (tests pin jax_default_device=cpu): native f64 path
    c = np.asarray(mm.matmul(jnp.asarray(a), jnp.asarray(b)))
    assert np.abs(c - ref).max() / np.abs(ref).max() < 1e-14
    # force the "TPU default" branch: the Ozaki kernels are pure XLA and
    # run (slowly) on CPU too, so the dispatch itself is testable hermetically.
    # Lower the measured-win-region gate so test-sized shapes route to Ozaki.
    monkeypatch.setattr(mm, "_tpu_is_default", lambda: True)
    monkeypatch.setattr(mm, "_use_pallas", lambda *_: False)
    monkeypatch.setattr(mm, "_OZAKI_MIN_ELEMS", 256**3)
    monkeypatch.setattr(mm, "_OZAKI_MIN_DIM", 256)
    A = rng.standard_normal((256, 256))
    B = rng.standard_normal((256, 256))
    REF = A @ B
    c = np.asarray(mm.matmul(jnp.asarray(A), jnp.asarray(B)))
    assert np.abs(c - REF).max() / np.abs(REF).max() < 1e-13
    c6 = np.asarray(mm.matmul(jnp.asarray(A), jnp.asarray(B), precision=Precision.Fast))
    assert np.abs(c6 - REF).max() / np.abs(REF).max() < 1e-8
    ce = np.asarray(mm.matmul(jnp.asarray(A), jnp.asarray(B), precision=Precision.Emulated))
    assert np.abs(ce - REF).max() / np.abs(REF).max() < 1e-14
    # below the gate: falls through to jnp.matmul even on "TPU"
    csmall = np.asarray(mm.matmul(jnp.asarray(a), jnp.asarray(b)))
    assert np.abs(csmall - ref).max() / np.abs(ref).max() < 1e-14
    ac = jnp.asarray(A + 1j * A[::-1])
    bc = jnp.asarray(B - 1j * B)
    cc = np.asarray(mm.matmul(ac, bc))
    refc = np.asarray(ac) @ np.asarray(bc)
    assert np.abs(cc - refc).max() / np.abs(refc).max() < 1e-12
