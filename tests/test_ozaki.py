"""Ozaki split-int8 f64 GEMM (slate_tpu/ops/ozaki.py) — accuracy gates
against numpy f64, including mixed row magnitudes, k-chunking, and the
digit-boundary adversarial case."""

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.ops.ozaki import matmul_f64


@pytest.mark.parametrize("shape", [(64, 64, 64), (128, 300, 65), (96, 8192, 64)])
@pytest.mark.parametrize("scale", [1.0, 1e8, 1e-12])
def test_matmul_f64_accuracy(shape, scale):
    m, k, n = shape
    rng = np.random.default_rng(0)
    a = rng.standard_normal((m, k)) * scale
    a[::3] *= 1e6  # mixed row magnitudes exercise the per-row exponents
    b = rng.standard_normal((k, n))
    c = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
    ref = a @ b
    rel = np.abs(c - ref).max() / np.abs(ref).max()
    assert rel < 1e-13, rel


def test_matmul_f64_adversarial_boundaries():
    # every element just below a power of two: all digit planes saturate
    a = np.full((64, 8192), 0.9999999999)
    b = np.full((8192, 64), -0.9999999999)
    c = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
    ref = a @ b
    rel = np.abs(c - ref).max() / np.abs(ref).max()
    assert rel < 1e-13, rel


def test_matmul_f64_zero_rows_and_fast_variant():
    rng = np.random.default_rng(1)
    a = rng.standard_normal((32, 50))
    a[5] = 0.0  # zero row: exponent guard
    b = rng.standard_normal((50, 32))
    c = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
    assert np.abs(c - a @ b).max() / np.abs(a @ b).max() < 1e-13
    # reduced-slice variant trades accuracy for speed but stays ~f32-pair
    c6 = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b), n_slices=6))
    assert np.abs(c6 - a @ b).max() / np.abs(a @ b).max() < 1e-8


def test_matmul_f64_rejects_f32():
    with pytest.raises(TypeError):
        matmul_f64(jnp.ones((4, 4), jnp.float32), jnp.ones((4, 4), jnp.float32))
