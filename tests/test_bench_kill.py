"""bench.py kill-path hardening (ISSUE 9 satellite): a SIGTERM delivered
mid-extra (what ``timeout -k`` sends before SIGKILL) must still leave a
parseable final JSON line on stdout AND a parseable atomic partial file —
the BENCH_r05 failure mode was rc=124 with parsed=null."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


@pytest.mark.parametrize("sig", [signal.SIGTERM])
def test_sigterm_mid_extra_yields_parseable_output(tmp_path, sig):
    partial = tmp_path / "bench_partial.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["SLATE_TPU_BENCH_PARTIAL"] = str(partial)
    env.pop("SLATE_TPU_OBS_MEM", None)
    proc = subprocess.Popen(
        [sys.executable, BENCH, "--selftest-kill"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(tmp_path),
    )
    try:
        # wait for the harness to reach the blocked mid-extra state
        deadline = time.time() + 120
        ready = False
        lines = []
        while time.time() < deadline:
            line = proc.stderr.readline()
            if not line:
                time.sleep(0.05)
                continue
            lines.append(line)
            if "SELFTEST_READY" in line:
                ready = True
                break
        assert ready, f"selftest never armed: {''.join(lines)[-2000:]}"
        proc.send_signal(sig)
        out, err = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 124, (proc.returncode, out[-500:])
    # the driver's tail parser: the LAST parsable JSON line wins
    parsed = None
    for line in reversed(out.strip().splitlines()):
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    assert parsed is not None, f"no parsable line in tail: {out[-500:]}"
    assert "metric" in parsed and isinstance(parsed.get("value"), (int, float))
    # the SIGKILL-proof twin: the atomically-rewritten partial file
    assert partial.exists()
    twin = json.loads(partial.read_text())
    assert twin["metric"] == parsed["metric"]
