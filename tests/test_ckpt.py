"""Checkpointed k-loops + elastic resume (ISSUE 12 + 13).

Acceptance surface, kept LEAN (one shared n=64/nb=8 shape set, segment
jits reused across tests via the process jit cache, no clear_caches):
kill at step k → resume on the SAME mesh is bitwise-identical to the
uninterrupted factorization for potrf, LU-nopiv, partial-pivot LU, and
the MULTI-ARRAY-carry CAQR; resume on a RESHAPED mesh lands the
bitwise-same solution (tile-stack ops) or a structured refusal
(grid-locked geqrf/he2hb carries); checkpoint off is jaxpr-identical to
the current driver path (potrf / geqrf / he2hb); an in-segment kill
loses exactly kill.k − last_snapshot steps; async snapshots are
bitwise-equal to sync; a monitored nopiv factor growth-aborts mid-loop;
the kill injector is seeded-deterministic and one-shot; recovery-cost
counters reach the RunReport ft section.  The multi-op reshaped sweep
and the he2hb kill→resume sweep are ``-m slow``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.ft import ckpt, elastic, inject
from slate_tpu.ft.policy import ft_counter_values
from slate_tpu.parallel import from_dense, make_mesh, to_dense
from slate_tpu.parallel.dist_chol import potrf_dist
from slate_tpu.parallel.dist_lu import getrf_nopiv_dist, getrf_pp_dist
from slate_tpu.parallel.dist_qr import geqrf_dist
from slate_tpu.parallel.dist_twostage import he2hb_dist
from slate_tpu.types import Option, SlateError

from conftest import cpu_devices

N, NB = 64, 8
NT = N // NB
EVERY = 3  # segment boundaries 3, 6 — kill at 4 loses exactly 1 step


def mesh24():
    return make_mesh(2, 4, devices=cpu_devices(8))


def mesh42():
    return make_mesh(4, 2, devices=cpu_devices(8))


def _operand(kind, seed=7):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((N, N))
    if kind == "spd":
        a = a @ a.T / N + 2 * np.eye(N)
    elif kind == "dom":
        a = np.tril(a) + N * np.eye(N) + np.triu(
            rng.standard_normal((N, N)), 1)
    return jnp.asarray(a)


_CASES = {
    "potrf": ("spd", potrf_dist, ckpt.potrf_ckpt),
    "getrf_nopiv": ("dom", getrf_nopiv_dist, ckpt.getrf_nopiv_ckpt),
    "getrf_pp": ("general", getrf_pp_dist, ckpt.getrf_pp_ckpt),
}


def _run_case(op, mesh):
    kind, plain, ckpted = _CASES[op]
    d = from_dense(_operand(kind), mesh, NB, diag_pad_one=True)
    return d, plain(d), ckpted


def _assert_tree_bitwise(ref, got, what):
    for r, g in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g),
                                      err_msg=what)


@pytest.mark.parametrize("op", list(_CASES))
def test_kill_resume_bitwise_same_mesh(op):
    mesh = mesh24()
    d, ref, ckpted = _run_case(op, mesh)
    # uninterrupted checkpointed chain == fused kernel, bitwise
    _assert_tree_bitwise(ref, ckpted(d, every=EVERY), f"{op} ckpt vs fused")
    # seeded kill inside the second segment -> Preempted with the step-3
    # snapshot; resume must reproduce the fused result bitwise
    with inject.fault_scope(inject.FaultPlan([inject.KillFault(op, 4)])):
        with pytest.raises(ckpt.Preempted) as ei:
            ckpted(d, every=EVERY)
    ck = ei.value.checkpoint
    assert ck is not None and ck.step == 3 and ck.op == op
    _assert_tree_bitwise(ref, elastic.resume(ck, mesh), f"{op} resume")


def test_resume_reshaped_mesh_potrf():
    mesh = mesh24()
    d, ref, ckpted = _run_case("potrf", mesh)
    with inject.fault_scope(
        inject.FaultPlan([inject.KillFault("potrf", 4)])
    ), pytest.raises(ckpt.Preempted) as ei:
        ckpted(d, every=EVERY)
    res, info = elastic.resume(ei.value.checkpoint, mesh42())
    # the redistribution moves exact bytes: the solution is bitwise
    np.testing.assert_array_equal(
        np.asarray(to_dense(ref[0])), np.asarray(to_dense(res)))
    assert int(info) == int(ref[1])
    assert ft_counter_values()["ckpt_reshards"] >= 1


@pytest.mark.slow
@pytest.mark.parametrize("op", ["getrf_nopiv", "getrf_pp"])
def test_resume_reshaped_mesh_lu(op):
    mesh = mesh24()
    d, ref, ckpted = _run_case(op, mesh)
    with inject.fault_scope(
        inject.FaultPlan([inject.KillFault(op, 5)])
    ), pytest.raises(ckpt.Preempted) as ei:
        ckpted(d, every=EVERY)
    res = elastic.resume(ei.value.checkpoint, mesh42())
    np.testing.assert_array_equal(
        np.asarray(to_dense(ref[0])), np.asarray(to_dense(res[0])))
    if op == "getrf_pp":
        # pivot choices are data-driven: the permutation's data prefix
        # must survive the re-based padded row space exactly
        np.testing.assert_array_equal(
            np.asarray(ref[1])[:N], np.asarray(res[1])[:N])


def test_checkpoint_disk_roundtrip(tmp_path):
    mesh = mesh24()
    d, ref, ckpted = _run_case("potrf", mesh)
    with inject.fault_scope(
        inject.FaultPlan([inject.KillFault("potrf", 4)])
    ), pytest.raises(ckpt.Preempted) as ei:
        ckpted(d, every=EVERY)
    ck = ei.value.checkpoint
    ck2 = ckpt.Checkpoint.load(ck.save(str(tmp_path / "ck.npz")))
    assert (ck2.op, ck2.step, ck2.every, ck2.grid) == (
        ck.op, ck.step, ck.every, ck.grid)
    np.testing.assert_array_equal(ck.tiles, ck2.tiles)
    _assert_tree_bitwise(ref, elastic.resume(ck2, mesh), "disk resume")


def test_ckpt_off_is_driver_jaxpr_identical():
    """Option.Checkpoint off/absent routes potrf_mesh through the exact
    pre-checkpoint path — same jaxpr, not merely same numbers."""
    from slate_tpu.parallel import potrf_mesh

    mesh = mesh24()
    a = _operand("spd")

    def jx(opts):
        return str(jax.make_jaxpr(
            lambda x: potrf_mesh(x, mesh, NB, opts))(a))

    base = jx(None)
    assert jx({Option.Checkpoint: "off"}) == base
    assert jx({Option.Checkpoint: 0}) == base


def test_kill_injector_deterministic_and_one_shot():
    k1 = inject.seeded_kill(5, "potrf", NT)
    k2 = inject.seeded_kill(5, "potrf", NT)
    assert (k1.op, k1.k) == (k2.op, k2.k) and 1 <= k1.k < NT
    plan = inject.FaultPlan([inject.KillFault("potrf", 4)])
    with inject.fault_scope(plan):
        (kf,) = inject.armed_kills("potrf")
        plan.consume_fault(kf)
        assert inject.armed_kills("potrf") == []  # one-shot: resume clean
    persist = inject.FaultPlan([inject.KillFault("potrf", 4, persist=True)])
    with inject.fault_scope(persist):
        (kf,) = inject.armed_kills("potrf")
        persist.consume_fault(kf)
        assert len(inject.armed_kills("potrf")) == 1  # re-kills on resume
    # kills never leak into the kernel fault spec
    with inject.fault_scope(plan):
        ints, _ = inject.spec_arrays("potrf")
        assert not ints[:, 0].any()


def test_ckpt_counters_reach_runreport():
    from slate_tpu.obs import report

    mesh = mesh24()
    d, _ref, ckpted = _run_case("potrf", mesh)
    before = ft_counter_values()
    with inject.fault_scope(
        inject.FaultPlan([inject.KillFault("potrf", 4)])
    ), pytest.raises(ckpt.Preempted) as ei:
        ckpted(d, every=EVERY)
    elastic.resume(ei.value.checkpoint, mesh)
    after = ft_counter_values()
    assert after["ckpt_kills"] == before["ckpt_kills"] + 1
    assert after["ckpt_lost_steps"] == before["ckpt_lost_steps"] + 1
    assert after["ckpt_resumes"] == before["ckpt_resumes"] + 1
    assert after["ckpt_snapshots"] > before["ckpt_snapshots"]
    assert after["ckpt_snapshot_bytes"] > before["ckpt_snapshot_bytes"]
    rep = report.make_report("ckpt_counters_probe")
    assert rep["ft"]["ckpt_resumes"] >= after["ckpt_resumes"]
    assert report.validate_report(rep) == []


def test_ckpt_num_monitor_gauges_match_fused():
    """The NumMonitor gauges ride the segment carry: a checkpointed run
    records the same growth/margin values as the fused kernel."""
    from slate_tpu.obs import numerics as num

    mesh = mesh24()
    d = from_dense(_operand("spd"), mesh, NB, diag_pad_one=True)
    num.clear_last("potrf")
    potrf_dist(d, num_monitor="on")
    fused = num.last_gauges("potrf")
    num.clear_last("potrf")
    ckpt.potrf_ckpt(d, every=EVERY, num_monitor="on")
    segd = num.last_gauges("potrf")
    assert fused and segd
    for key in fused:
        assert segd[key] == fused[key], (key, fused, segd)


# ---------------------------------------------------------------------------
# ISSUE 13: multi-array carries (geqrf / he2hb), in-segment kills, async
# snapshots, growth abort — same lean n=64/nb=8 shape set, segment jits
# shared across tests via the process jit cache.
# ---------------------------------------------------------------------------


def test_qr_kill_resume_bitwise(tmp_path):
    """The CAQR chain's MULTI-ARRAY carry (tile stack + T_loc + tree V/T
    stacks): uninterrupted chain == fused kernel bitwise, kill→resume
    (through a disk round trip) bitwise, and a reshaped-grid resume is
    REFUSED with a structured error (the aux carries are grid-locked)."""
    mesh = mesh24()
    d = from_dense(_operand("general"), mesh, NB)
    ref = geqrf_dist(d)
    _assert_tree_bitwise(ref, ckpt.geqrf_ckpt(d, every=EVERY),
                         "geqrf ckpt vs fused")
    with inject.fault_scope(
        inject.FaultPlan([inject.KillFault("geqrf", 4)])
    ), pytest.raises(ckpt.Preempted) as ei:
        ckpt.geqrf_ckpt(d, every=EVERY)
    ck = ei.value.checkpoint
    assert ck is not None and ck.step == 3 and ck.op == "geqrf"
    assert set(ck.arrays) == {"tls", "tvs", "tts"}
    ck = ckpt.Checkpoint.load(ck.save(str(tmp_path / "qr.npz")))
    _assert_tree_bitwise(ref, elastic.resume(ck, mesh), "geqrf resume")
    with pytest.raises(SlateError, match="grid-locked"):
        elastic.resume(ck, mesh42())


def test_qr_ckpt_orth_gauge_bitwise_and_recorded():
    """ISSUE 14 satellite (ROADMAP "NumMonitor gauges through the QR/eig
    segment chains"): the monitored CAQR chain carries the per-panel
    reflector/τ orthogonality-loss proxy — results stay BITWISE equal to
    the unmonitored chain (and hence the fused kernel), the gauge lands
    as num.qr_orth_margin / qr_orth_loss_max (eps-class for a healthy
    operand), and off mode records nothing."""
    from slate_tpu.obs import numerics as num

    mesh = mesh24()
    d = from_dense(_operand("general"), mesh, NB)
    ref = geqrf_dist(d)
    num.reset()
    _assert_tree_bitwise(ref, ckpt.geqrf_ckpt(d, every=EVERY,
                                              num_monitor="on"),
                         "monitored geqrf ckpt vs fused")
    vals = num.num_counter_values()
    assert 0.0 < vals["qr_orth_loss_max"] < 1e-10  # ~eps64, healthy panel
    assert num.last_gauges("geqrf")["qr_orth_loss"] \
        == vals["qr_orth_loss_max"]
    # ISSUE 15 acceptance: the FUSED (non-checkpointed) monitored loop
    # reports the SAME gauge bitwise on the same operand (max folds are
    # exact, so segment boundaries cannot move the running max) — and
    # its results stay bitwise too
    chained_gauge = vals["qr_orth_loss_max"]
    num.reset()
    _assert_tree_bitwise(ref, geqrf_dist(d, num_monitor="on"),
                         "monitored fused geqrf vs plain")
    assert num.last_gauges("geqrf")["qr_orth_loss"] == chained_gauge
    # off mode: the plain (unchanged) segment chain — already compiled by
    # test_qr_kill_resume_bitwise — records nothing (the kill->resume
    # gauge flow itself rides the same snapshot gauges dict the potrf/LU
    # chains tier-1-test; no extra segment compiles here)
    num.reset()
    ckpt.geqrf_ckpt(d, every=EVERY, num_monitor="off")
    assert num.num_counter_values()["qr_orth_loss_max"] == 0.0


def test_in_segment_kill_loses_steps_since_snapshot():
    """KillFault(in_segment=True): the partial segment really executes
    (then dies), the loss counter reads exactly kill.k − last_snapshot
    steps, and resume from the boundary snapshot is still bitwise."""
    mesh = mesh24()
    d, ref, ckpted = _run_case("potrf", mesh)
    before = ft_counter_values()
    with inject.fault_scope(
        inject.FaultPlan([inject.KillFault("potrf", 5, in_segment=True)])
    ), pytest.raises(ckpt.Preempted) as ei:
        ckpted(d, every=EVERY)
    after = ft_counter_values()
    ck = ei.value.checkpoint
    assert ck is not None and ck.step == 3  # last snapshot boundary
    assert after["ckpt_lost_steps"] - before["ckpt_lost_steps"] == 5 - 3
    assert after["ckpt_inseg_kills"] - before["ckpt_inseg_kills"] == 1
    _assert_tree_bitwise(ref, elastic.resume(ck, mesh), "inseg resume")


def test_async_snapshots_bitwise():
    """Async snapshots (copy_to_host_async fenced at the next boundary)
    are bitwise-equal to sync ones: same results, same snapshot bytes on
    a kill, counters record the overlap."""
    mesh = mesh24()
    d, ref, ckpted = _run_case("potrf", mesh)
    before = ft_counter_values()
    _assert_tree_bitwise(
        ref, ckpt.potrf_ckpt(d, every=EVERY, async_snapshots=True),
        "async ckpt vs fused")
    after = ft_counter_values()
    assert after["ckpt_async_snapshots"] > before["ckpt_async_snapshots"]
    assert after["ckpt_snapshots"] > before["ckpt_snapshots"]  # fenced+counted

    def killed(async_snapshots):
        with inject.fault_scope(
            inject.FaultPlan([inject.KillFault("potrf", 4)])
        ), pytest.raises(ckpt.Preempted) as ei:
            ckpt.potrf_ckpt(d, every=EVERY, async_snapshots=async_snapshots)
        return ei.value.checkpoint

    ck_async, ck_sync = killed(True), killed(False)
    assert ck_async.step == ck_sync.step == 3
    np.testing.assert_array_equal(ck_async.tiles, ck_sync.tiles)


def test_growth_abort_nopiv_mid_loop():
    """ROADMAP "close the control loop": a monitored checkpointed nopiv
    LU whose running growth crosses GROWTH_THRESHOLD aborts at the next
    segment boundary (structured GrowthAbort naming the step) instead of
    completing a garbage factor; growth_abort=False opts out and
    completes; the num.growth_aborts counter moves."""
    from slate_tpu.obs.numerics import GrowthAbort, num_counter_values

    mesh = mesh24()
    g = np.array(_operand("dom"))
    g[0, 0] = 1e-9  # tiny leading pivot: nopiv growth explodes at step 0
    d = from_dense(jnp.asarray(g), mesh, NB, diag_pad_one=True)
    before = num_counter_values()
    with pytest.raises(GrowthAbort) as ei:
        ckpt.getrf_nopiv_ckpt(d, every=EVERY, num_monitor="on")
    after = num_counter_values()
    assert ei.value.op == "getrf_nopiv" and ei.value.step == EVERY
    assert ei.value.growth > ei.value.threshold
    assert after["growth_aborts"] == before["growth_aborts"] + 1
    lu, info = ckpt.getrf_nopiv_ckpt(d, every=EVERY, num_monitor="on",
                                     growth_abort=False)
    assert int(info) == 0  # finite garbage completes when opted out


@pytest.mark.slow
def test_he2hb_kill_resume_bitwise():
    """The two-stage eig stage-1 reduction's multi-array carry (tiles →
    band + sharded reflectors + compact-WY stacks): chain == fused
    bitwise, kill→resume bitwise, reshaped-grid resume refused."""
    mesh = mesh24()
    d = from_dense(_operand("spd"), mesh, NB)
    ref = he2hb_dist(d)
    _assert_tree_bitwise(ref, ckpt.he2hb_ckpt(d, every=2),
                         "he2hb ckpt vs fused")
    with inject.fault_scope(
        inject.FaultPlan([inject.KillFault("he2hb", 3)])
    ), pytest.raises(ckpt.Preempted) as ei:
        ckpt.he2hb_ckpt(d, every=2)
    ck = ei.value.checkpoint
    assert ck is not None and ck.step == 2 and set(ck.arrays) == {
        "vqs", "tqs"}
    _assert_tree_bitwise(ref, elastic.resume(ck, mesh), "he2hb resume")
    with pytest.raises(SlateError, match="grid-locked"):
        elastic.resume(ck, mesh42())


def test_ckpt_off_geqrf_jaxpr_identical():
    """Option.Checkpoint off/absent routes geqrf_mesh through the exact
    pre-checkpoint path — same jaxpr, not merely same numbers."""
    from slate_tpu.parallel import geqrf_mesh

    mesh = mesh24()
    a = _operand("general")

    def jx(opts):
        return str(jax.make_jaxpr(
            lambda x: geqrf_mesh(x, mesh, NB, opts))(a))

    base = jx(None)
    assert jx({Option.Checkpoint: "off"}) == base
    assert jx({Option.Checkpoint: 0}) == base


def test_ckpt_off_he2hb_jaxpr_identical():
    """he2hb_ckpt with Checkpoint off routes to the untouched fused
    he2hb_dist — same jaxpr (trace-only: nothing executes)."""
    mesh = mesh24()
    d = from_dense(_operand("spd"), mesh, NB)

    def rewrap(t):
        from slate_tpu.parallel.dist import DistMatrix

        return DistMatrix(tiles=t, m=d.m, n=d.n, nb=d.nb, mesh=mesh)

    base = str(jax.make_jaxpr(lambda t: he2hb_dist(rewrap(t)))(d.tiles))
    off = str(jax.make_jaxpr(
        lambda t: ckpt.he2hb_ckpt(rewrap(t), every=None))(d.tiles))
    assert off == base


def test_growth_abort_survives_resume():
    """Review fix: the growth-abort gate is persisted in the Checkpoint,
    so a preemption BEFORE the gauge crosses cannot smuggle a garbage
    no-pivot factor past the abort — the resumed run still raises."""
    from slate_tpu.obs.numerics import GrowthAbort

    mesh = mesh24()
    g = np.array(_operand("dom"))
    # isolate a tiny pivot at factor step 6: no updates land on (48, 48)
    # (row/col 48 zero left of/above the diagonal), while the column
    # below and row right are O(1) — the step-6 elimination divides by
    # 1e-9 and growth explodes only then, AFTER the step-3 snapshot
    g[48, :48] = 0.0
    g[:48, 48] = 0.0
    g[48, 48] = 1e-9
    g[49:, 48] = 1.0
    g[48, 49:] = 1.0
    d = from_dense(jnp.asarray(g), mesh, NB, diag_pad_one=True)
    with inject.fault_scope(
        inject.FaultPlan([inject.KillFault("getrf_nopiv", 4)])
    ), pytest.raises(ckpt.Preempted) as ei:
        ckpt.getrf_nopiv_ckpt(d, every=EVERY, num_monitor="on")
    ck = ei.value.checkpoint
    assert ck is not None and ck.growth_abort
    with pytest.raises(GrowthAbort):
        elastic.resume(ck, mesh)
