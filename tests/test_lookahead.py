"""Lookahead pipelining (ISSUE 3): Option.Lookahead consumed end-to-end.

Contracts under test, on the forced 8-device CPU mesh:

1. Depth 0 reproduces the strict broadcast→update schedule and depth >= 1
   reorders ONLY independent work — results are BITWISE identical across
   depths for every pipelined mesh kernel (summa / dist_chol / dist_lu /
   dist_trsm / dist_blas3).
2. The option plumbs through the driver (`opts`) and api facades.
3. Lookahead changes WHEN bytes move (audit record layout: prologue
   records at multiplicity 1 split off the loop records) but not HOW MANY
   (total audited payload is unchanged at any depth).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.parallel import (
    from_dense,
    gemm_mesh,
    gemm_summa,
    make_mesh,
    potrf_dist,
    to_dense,
    trsm_dist,
)
from slate_tpu.parallel.comm import comm_audit, la_depth, prefetch_bcast
from slate_tpu.parallel.dist_blas3 import hemm_summa, her2k_dist, trmm_dist
from slate_tpu.parallel.dist_chol import pbtrf_band_dist
from slate_tpu.parallel.dist_lu import (
    getrf_nopiv_dist,
    getrf_pp_dist,
    getrf_tntpiv_dist,
)
from slate_tpu.parallel.dist_trsm import trsm_dist_right
from slate_tpu.types import (
    Diag,
    MethodGemm,
    MethodHemm,
    MethodTrsm,
    Op,
    Option,
    Side,
    Uplo,
    get_option,
)

from conftest import cpu_devices

DEPTHS = (0, 1, 2)
N, NB = 64, 8


def mesh24():
    return make_mesh(2, 4, devices=cpu_devices(8))


def _rand(rng, m, n, cplx=False):
    a = rng.standard_normal((m, n))
    if cplx:
        a = a + 1j * rng.standard_normal((m, n))
    return jnp.asarray(a)


def _assert_bitwise(outs, label):
    for la in DEPTHS[1:]:
        np.testing.assert_array_equal(
            np.asarray(outs[la]), np.asarray(outs[0]),
            err_msg=f"{label}: depth {la} differs from the strict schedule",
        )


# ---------------------------------------------------------------------------
# depth 0/1/2 bitwise equivalence, kernel by kernel
# ---------------------------------------------------------------------------


def test_lookahead_gemm_summa_bitwise(rng):
    mesh = mesh24()
    a = from_dense(_rand(rng, N, N), mesh, NB)
    b = from_dense(_rand(rng, N, N), mesh, NB)
    outs = {
        la: to_dense(gemm_summa(1.0, a, b, method=MethodGemm.GemmC, lookahead=la))
        for la in DEPTHS
    }
    _assert_bitwise(outs, "gemm_summa")


def test_lookahead_potrf_dist_bitwise(rng):
    mesh = mesh24()
    a = _rand(rng, N, N)
    spd = a @ a.T + N * jnp.eye(N)
    ad = from_dense(spd, mesh, NB, diag_pad_one=True)
    outs = {}
    for la in DEPTHS:
        l, info = potrf_dist(ad, lookahead=la)
        assert int(info) == 0
        outs[la] = to_dense(l)
    _assert_bitwise(outs, "potrf_dist")


def test_lookahead_pbtrf_band_dist_bitwise(rng):
    from slate_tpu.core.matrix import band_project

    mesh = mesh24()
    kd = 18
    a = _rand(rng, N, N)
    spd = band_project(a @ a.T + N * jnp.eye(N), kd, kd)
    ad = from_dense(spd, mesh, NB, diag_pad_one=True)
    outs = {}
    for la in DEPTHS:
        l, info = pbtrf_band_dist(ad, kd, lookahead=la)
        assert int(info) == 0
        outs[la] = to_dense(l)
    _assert_bitwise(outs, "pbtrf_band_dist")


@pytest.mark.parametrize(
    "factor", [getrf_nopiv_dist, getrf_tntpiv_dist, getrf_pp_dist],
    ids=["nopiv", "tntpiv", "pp"],
)
def test_lookahead_dist_lu_bitwise(rng, factor):
    mesh = mesh24()
    a = rng.standard_normal((N, N))
    if factor is getrf_nopiv_dist:  # no pivoting: keep it diagonally safe
        a = np.tril(a) + N * np.eye(N) + np.triu(rng.standard_normal((N, N)), 1)
    ad = from_dense(jnp.asarray(a), mesh, NB, diag_pad_one=True)
    outs = {}
    for la in DEPTHS:
        res = factor(ad, lookahead=la)
        lu, info = res[0], res[-1]
        assert int(info) == 0
        perm = res[1] if len(res) == 3 else None
        outs[la] = (
            np.asarray(to_dense(lu)),
            None if perm is None else np.asarray(perm),
        )
    for la in DEPTHS[1:]:
        np.testing.assert_array_equal(outs[la][0], outs[0][0])
        if outs[0][1] is not None:
            np.testing.assert_array_equal(outs[la][1], outs[0][1])


@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
@pytest.mark.parametrize("op", [Op.NoTrans, Op.Trans])
def test_lookahead_trsm_dist_bitwise(rng, uplo, op):
    mesh = mesh24()
    t = np.tril(rng.standard_normal((N, N))) + N * np.eye(N)
    ad = from_dense(jnp.asarray(t), mesh, NB, diag_pad_one=True)
    bd = from_dense(_rand(rng, N, 2 * NB), mesh, NB)
    for method in (MethodTrsm.TrsmB, MethodTrsm.TrsmA):
        outs = {
            la: to_dense(trsm_dist(ad, bd, uplo, op, method=method, lookahead=la))
            for la in DEPTHS
        }
        _assert_bitwise(outs, f"trsm_dist[{uplo},{op},{method}]")


def test_lookahead_trsm_dist_right_bitwise(rng):
    mesh = mesh24()
    t = np.tril(rng.standard_normal((N, N))) + N * np.eye(N)
    ad = from_dense(jnp.asarray(t), mesh, NB, diag_pad_one=True)
    bd = from_dense(_rand(rng, N, N), mesh, NB)
    for op in (Op.NoTrans, Op.Trans):
        outs = {
            la: to_dense(trsm_dist_right(ad, bd, Uplo.Lower, op, lookahead=la))
            for la in DEPTHS
        }
        _assert_bitwise(outs, f"trsm_dist_right[{op}]")


def test_lookahead_blas3_bitwise(rng):
    mesh = mesh24()
    h = _rand(rng, N, N, cplx=True)
    hd = from_dense(h + jnp.conj(h).T, mesh, NB)
    bd = from_dense(_rand(rng, N, N, cplx=True), mesh, NB)
    outs = {
        la: to_dense(
            hemm_summa(Side.Left, 1.0, hd, bd, uplo=Uplo.Lower,
                       method=MethodHemm.HemmC, lookahead=la)
        )
        for la in DEPTHS
    }
    _assert_bitwise(outs, "hemm_summa")

    t = np.tril(rng.standard_normal((N, N))) + np.eye(N)
    td = from_dense(jnp.asarray(t), mesh, NB, diag_pad_one=True)
    gd = from_dense(_rand(rng, N, N), mesh, NB)
    outs = {
        la: to_dense(
            trmm_dist(Side.Left, Uplo.Lower, Op.NoTrans, Diag.NonUnit, 1.0,
                      td, gd, lookahead=la)
        )
        for la in DEPTHS
    }
    _assert_bitwise(outs, "trmm_dist")

    a2 = from_dense(_rand(rng, N, N), mesh, NB)
    b2 = from_dense(_rand(rng, N, N), mesh, NB)
    outs = {
        la: to_dense(her2k_dist(1.0, a2, b2, lookahead=la)) for la in DEPTHS
    }
    _assert_bitwise(outs, "her2k_dist")


def test_lookahead_depth_clamps_past_trip_count(rng):
    # depth > nt must clamp (all panels prefetched up front), not crash
    mesh = mesh24()
    a = from_dense(_rand(rng, N, N), mesh, NB)
    b = from_dense(_rand(rng, N, N), mesh, NB)
    deep = to_dense(gemm_summa(1.0, a, b, method=MethodGemm.GemmC, lookahead=99))
    strict = to_dense(gemm_summa(1.0, a, b, method=MethodGemm.GemmC, lookahead=0))
    np.testing.assert_array_equal(np.asarray(deep), np.asarray(strict))
    assert la_depth(99, 8) == 8 and la_depth(None, 8) == 1 and la_depth(-3, 8) == 0


# ---------------------------------------------------------------------------
# option plumbing: drivers, api facades, defaults
# ---------------------------------------------------------------------------


def test_lookahead_option_default_is_one():
    assert get_option(None, Option.Lookahead) == 1
    assert get_option({Option.Lookahead: 3}, Option.Lookahead) == 3
    assert get_option({"lookahead": 2}, Option.Lookahead) == 2


def test_lookahead_plumbs_through_mesh_driver_opts(rng):
    """gemm_mesh(opts={Lookahead: d}) must reach the kernel: the audit
    record layout is the fingerprint (depth 0 -> all records scoped at
    multiplicity kt; depth 2 -> 2 prologue records per operand at
    multiplicity 1 + loop records at kt - 2)."""
    mesh = mesh24()
    a, b = _rand(rng, N, N), _rand(rng, N, N)
    kt = N // NB

    def records_for(depth):
        jax.clear_caches()  # audit hooks record at trace time only
        with comm_audit() as recs:
            gemm_mesh(1.0, a, b, mesh, nb=NB,
                      opts={Option.Lookahead: depth}).block_until_ready()
        return [(op, nb_, m) for op, nb_, m in recs]

    strict = records_for(0)
    deep = records_for(2)
    assert {m for _, _, m in strict} == {kt}
    # depth 2: each of the two psum streams shows 2 prologue fetches + a
    # shortened loop — the "when" changed...
    assert sorted({m for _, _, m in deep}) == [1, kt - 2]
    # ...but the total payload did not (the "how many" invariant)
    total = lambda rs: sum(nb_ * m for _, nb_, m in rs)
    assert total(deep) == total(strict)


def test_lookahead_factor_kernels_keep_audit_records_identical(rng):
    """The deferred-update pipeline (potrf) keeps the very same audit
    records: panel broadcasts stay in the loop at full multiplicity —
    bytes move at execution time (XLA overlap), not at the audit level."""
    mesh = mesh24()
    a = _rand(rng, N, N)
    spd = a @ a.T + N * jnp.eye(N)
    ad = from_dense(spd, mesh, NB, diag_pad_one=True)

    def records_for(depth):
        jax.clear_caches()
        with comm_audit() as recs:
            potrf_dist(ad, lookahead=depth)[0].tiles.block_until_ready()
        return sorted(recs)

    assert records_for(1) == records_for(0)


def test_lookahead_accepted_by_api_facades(rng):
    import slate_tpu.api as api

    a = _rand(rng, 32, 32)
    b = _rand(rng, 32, 32)
    opts = {Option.Lookahead: 2}
    c = api.multiply(1.0, a, b, opts=opts)
    np.testing.assert_allclose(
        np.asarray(c), np.asarray(a) @ np.asarray(b), rtol=1e-12, atol=1e-10
    )
    t = jnp.asarray(np.tril(np.asarray(a)) + 32 * np.eye(32))
    x = api.triangular_solve(Side.Left, 1.0, t, b, opts=opts)
    np.testing.assert_allclose(
        np.asarray(t) @ np.asarray(x), np.asarray(b), rtol=1e-10, atol=1e-8
    )


def test_posv_mesh_opts_bitwise(rng):
    """Driver-level plumbing: the full posv chain (potrf + 2 trsm) under
    explicit strict/deep opts stays bitwise identical."""
    from slate_tpu.parallel import posv_mesh

    mesh = mesh24()
    a = rng.standard_normal((50, 50))
    spd = jnp.asarray(a @ a.T + 50 * np.eye(50))
    b = _rand(rng, 50, 3)
    outs = {}
    for la in DEPTHS:
        x, info = posv_mesh(spd, b, mesh, nb=NB, opts={Option.Lookahead: la})
        assert int(info) == 0
        outs[la] = x
    _assert_bitwise(outs, "posv_mesh")


# ---------------------------------------------------------------------------
# prefetch_bcast unit contract
# ---------------------------------------------------------------------------


def test_prefetch_bcast_fetch_counts():
    """d prologue + (nt - d) in-loop + 0 epilogue fetches == nt, and every
    step consumes its own panel exactly once, in order."""
    nt = 7
    for depth in (0, 1, 3, 7, 99):
        fetched, consumed = [], []

        def fetch(k):
            fetched.append(k)
            return jnp.zeros((2,)) + (k if isinstance(k, int) else 0)

        def consume(k, panel, acc):
            consumed.append(k)
            return acc + jnp.sum(panel)

        prefetch_bcast(nt, depth, fetch, consume, jnp.zeros(()))
        # trace-time counts: the loop body traces exactly once (even for a
        # zero-trip loop), so python-level fetch calls are d prologue + 1
        # loop body; consumes are d epilogue + 1 loop body
        d = min(max(depth, 0), nt)
        want = (d + 1) if d > 0 else 1
        assert len(fetched) == want, (depth, fetched)
        assert len(consumed) == want, (depth, consumed)


def test_gbtrf_lookahead_is_strict_schedule_invariant(rng):
    """gbtrf accepts Option.Lookahead for API symmetry but runs the
    STRICT schedule at every depth — the pivoted band step's swap column
    window slides with k and its exclusion set would depend on the pivot
    choices, so there is no legal deferred-update reorder (and no
    read-only operand to prefetch: every panel reads column k as updated
    by step k-1).  PR 3 documented this in the driver docstring; this
    test turns the note into an enforced invariant: the traced schedule
    must be IDENTICAL at every depth (not merely bitwise-equal outputs —
    a depth-dependent schedule that happened to agree numerically would
    still fail here), and execution must agree bitwise."""
    from slate_tpu.parallel.dist_lu import gbtrf_band_dist

    mesh = mesh24()
    kl = ku = 2 * NB
    a = rng.standard_normal((N, N))
    band = np.triu(np.tril(a, kl), -ku).T  # any band-limited matrix
    ad = from_dense(jnp.asarray(band + N * np.eye(N)), mesh, NB,
                    diag_pad_one=True)

    jaxprs = {
        la: str(jax.make_jaxpr(
            lambda x: gbtrf_band_dist(x, kl, ku, lookahead=la)
        )(ad))
        for la in (0, 1, 3)
    }
    assert jaxprs[1] == jaxprs[0], "gbtrf schedule must not depend on depth"
    assert jaxprs[3] == jaxprs[0], "gbtrf schedule must not depend on depth"

    outs = {}
    for la in (0, 2):
        lu, perm, info = gbtrf_band_dist(ad, kl, ku, lookahead=la)
        assert int(info) == 0
        outs[la] = (np.asarray(to_dense(lu)), np.asarray(perm))
    np.testing.assert_array_equal(outs[2][0], outs[0][0])
    np.testing.assert_array_equal(outs[2][1], outs[0][1])
