"""Cholesky family tests — residual gates mirroring test/test_potrf.cc,
test_posv.cc, test_potri.cc, test_pbsv.cc."""

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.linalg import (
    pbsv_array,
    posv_array,
    posv_mixed_array,
    posv_mixed_gmres_array,
    potrf_array,
    potri_array,
    potrs_array,
    trtri_array,
    trtrm_array,
)
from slate_tpu.types import Diag, Uplo
from slate_tpu.utils.testing import generate


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
def test_potrf(dtype, uplo):
    n = 50
    a = generate("spd", n, dtype=dtype, seed=1)
    astore = np.tril(a) if uplo == Uplo.Lower else np.triu(a)
    f, info = potrf_array(jnp.asarray(astore), uplo)
    assert int(info) == 0
    fn = np.asarray(f)
    if uplo == Uplo.Lower:
        resid = fn @ fn.conj().T - a
    else:
        resid = fn.conj().T @ fn - a
    assert np.abs(resid).max() / np.abs(a).max() < 1e-13


def test_potrf_large_recursive():
    n = 700  # > _NB: exercises recursion
    a = generate("spd", n, dtype=np.float64, seed=2)
    f, info = potrf_array(jnp.asarray(a), Uplo.Lower)
    fn = np.asarray(f)
    assert int(info) == 0
    assert np.abs(fn @ fn.T - a).max() / np.abs(a).max() < 1e-12


def test_potrf_not_spd():
    a = -np.eye(8)
    f, info = potrf_array(jnp.asarray(a), Uplo.Lower)
    assert int(info) == 1  # first pivot fails


def test_posv():
    n, nrhs = 80, 5
    a = generate("spd", n, dtype=np.float64, seed=3)
    b = generate("rands", n, nrhs, np.float64, seed=4)
    x, f, info = posv_array(jnp.asarray(a), jnp.asarray(b), Uplo.Lower)
    assert int(info) == 0
    resid = a @ np.asarray(x) - b
    assert np.abs(resid).max() / (np.abs(a).sum() * np.abs(x).max()) < 1e-14


def test_potrs_upper():
    n = 30
    a = generate("spd", n, dtype=np.complex128, seed=5)
    b = generate("rands", n, 3, np.complex128, seed=6)
    f, info = potrf_array(jnp.asarray(np.triu(a)), Uplo.Upper)
    x = potrs_array(f, jnp.asarray(b), Uplo.Upper)
    np.testing.assert_allclose(a @ np.asarray(x), b, atol=1e-10)


def test_potri():
    n = 40
    a = generate("spd", n, dtype=np.float64, seed=7)
    f, _ = potrf_array(jnp.asarray(a), Uplo.Lower)
    inv = np.asarray(potri_array(f, Uplo.Lower))
    inv_full = np.tril(inv) + np.tril(inv, -1).T
    np.testing.assert_allclose(inv_full @ a, np.eye(n), atol=1e-10)


def test_trtri():
    n = 60
    rng = np.random.default_rng(8)
    l = np.tril(rng.standard_normal((n, n))) + 3 * np.eye(n)
    inv = np.asarray(trtri_array(jnp.asarray(l), Uplo.Lower))
    np.testing.assert_allclose(inv @ l, np.eye(n), atol=1e-12)
    u = np.triu(rng.standard_normal((n, n))) + 3 * np.eye(n)
    invu = np.asarray(trtri_array(jnp.asarray(u), Uplo.Upper))
    np.testing.assert_allclose(invu @ u, np.eye(n), atol=1e-12)


def test_trtrm():
    n = 25
    rng = np.random.default_rng(9)
    l = np.tril(rng.standard_normal((n, n)))
    out = np.asarray(trtrm_array(jnp.asarray(l), Uplo.Lower))
    expect = np.tril(l.T @ l)
    np.testing.assert_allclose(out, expect, atol=1e-12)


def test_pbsv():
    n, kd = 60, 4
    rng = np.random.default_rng(10)
    a = rng.standard_normal((n, n))
    band = np.zeros((n, n))
    for d in range(-kd, kd + 1):
        band += np.diag(np.diag(a, d), d)
    spd = band @ band.T + n * np.eye(n)
    spd_band = np.zeros((n, n))
    for d in range(-kd, kd + 1):  # spd = band@band.T has bandwidth 2kd; rebuild kd-band SPD
        pass
    # construct a kd-banded SPD directly: diagonally dominant band
    ab = np.zeros((n, n))
    for d in range(-kd, kd + 1):
        ab += np.diag(rng.standard_normal(n - abs(d)), d)
    ab = (ab + ab.T) / 2 + (2 * kd + 2) * np.eye(n)
    b = rng.standard_normal((n, 2))
    x, f, info = pbsv_array(jnp.asarray(np.tril(ab)), jnp.asarray(b), kd, Uplo.Lower)
    assert int(info) == 0
    np.testing.assert_allclose(ab @ np.asarray(x), b, atol=1e-10)
    # factor stays banded
    fn = np.asarray(f)
    assert np.abs(np.tril(fn, -kd - 1)).max() == 0


def test_posv_mixed():
    n = 100
    a = generate("spd", n, dtype=np.float64, seed=11)
    b = generate("rands", n, 1, np.float64, seed=12)
    x, iters, done, info = posv_mixed_array(jnp.asarray(a), jnp.asarray(b), Uplo.Lower)
    assert bool(done)
    resid = np.abs(a @ np.asarray(x) - b).max()
    assert resid / np.abs(b).max() < 1e-12  # refined to f64 accuracy


def test_posv_mixed_gmres():
    n = 60
    a = generate("spd", n, dtype=np.float64, seed=13)
    b = generate("rands", n, 1, np.float64, seed=14)[:, 0]
    x, rnorm = posv_mixed_gmres_array(jnp.asarray(a), jnp.asarray(b), Uplo.Lower)
    resid = np.abs(a @ np.asarray(x) - b).max()
    assert resid / np.abs(b).max() < 1e-10


def test_potrf_scan_matches_recursive():
    # single-program scanned Cholesky (north-star sizes code path)
    from slate_tpu.linalg.chol import _potrf_scan

    rng = np.random.default_rng(41)
    for n in (100, 300):
        g = rng.standard_normal((n, n))
        a = g @ g.T + n * np.eye(n)
        l = np.tril(np.asarray(_potrf_scan(jnp.asarray(a), nb=64)))
        ref = np.linalg.cholesky(a)
        assert np.abs(l - ref).max() / np.abs(ref).max() < 1e-13


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_potrf_left_looking(dtype):
    # the f64 left-looking path (potrf_array dispatches here at n >= 4096;
    # exercised directly at small n with a small panel width)
    from slate_tpu.linalg.chol import _potrf_left_looking

    rng = np.random.default_rng(3)
    for n, nb in [(300, 64), (256, 128)]:
        g = rng.standard_normal((n, n))
        if np.issubdtype(dtype, np.complexfloating):
            g = g + 1j * rng.standard_normal((n, n))
        a = (g @ g.conj().T + n * np.eye(n)).astype(dtype)
        l = np.tril(np.asarray(_potrf_left_looking(jnp.asarray(a), nb)))
        resid = np.linalg.norm(l @ l.conj().T - a) / np.linalg.norm(a)
        assert resid < 1e-13, (n, nb, resid)


def test_potrf_left_looking_staged():
    # the staged per-panel-program variant (the n > 20480 f64 chip path:
    # one donated XLA program per panel caps peak HBM at ~one matrix)
    # must match the fused left-looking form exactly in math
    from slate_tpu.linalg.chol import potrf_left_looking_staged

    rng = np.random.default_rng(5)
    n, nb = 300, 64
    g = rng.standard_normal((n, n))
    a = g @ g.T + n * np.eye(n)
    l = np.tril(np.asarray(potrf_left_looking_staged(jnp.asarray(a), nb)))
    resid = np.linalg.norm(l @ l.T - a) / np.linalg.norm(a)
    assert resid < 1e-13, resid


@pytest.mark.parametrize("cond", [1e6, 1e12])
def test_potrf_scan_ill_conditioned(cond):
    # ADVICE r3: the explicit-inverse panel solve trades the trsm's
    # unconditional backward stability for O(eps * cond(L_kk)) — bound the
    # regression on a deliberately ill-conditioned fixture.  Geometric
    # spectrum: cond(A) = cond, cond(L_kk) <= sqrt(cond), so the residual
    # gate is c * n * eps * sqrt(cond) (c small); the well-conditioned
    # tests above keep the 3-eps-class gate.
    from slate_tpu.linalg.chol import _potrf_scan

    rng = np.random.default_rng(7)
    n = 256
    q, _ = np.linalg.qr(rng.standard_normal((n, n)))
    d = cond ** (-np.arange(n) / (n - 1))  # 1 .. 1/cond
    a = (q * d) @ q.T
    a = (a + a.T) / 2
    l = np.tril(np.asarray(_potrf_scan(jnp.asarray(a), nb=64)))
    resid = np.linalg.norm(l @ l.T - a) / np.linalg.norm(a)
    eps = np.finfo(np.float64).eps
    assert resid < 8 * n * eps * np.sqrt(cond), (resid, cond)
    assert np.isfinite(l).all()


@pytest.mark.parametrize("cond", [None, 1e8])
def test_potrf_ll_ozaki_cached(cond):
    # The digit-cache left-looking f64 path (potrf_array dispatches here on
    # TPU at 4096 <= n <= 20480): panels split once into int8 planes on a
    # fixed sqrt(diag)-bounded row grid, each update one plane-level GEMM.
    # Gate: n*eps-class residual on well- AND ill-conditioned fixtures
    # (the bound slack costs <= log2 sqrt(n) top bits; S=10 absorbs it).
    from slate_tpu.linalg.chol import _potrf_ll_ozaki

    rng = np.random.default_rng(11)
    n, nb = 384, 128
    g = rng.standard_normal((n, n))
    if cond is None:
        a = (g + g.T) / (2 * np.sqrt(n)) + 3 * np.eye(n)
    else:
        q, _ = np.linalg.qr(g)
        a = (q * cond ** (-np.arange(n) / (n - 1))) @ q.T
        a = (a + a.T) / 2
    l = np.tril(np.asarray(_potrf_ll_ozaki(jnp.asarray(a), nb=nb)))
    resid = np.linalg.norm(l @ l.T - a) / np.linalg.norm(a)
    eps = np.finfo(np.float64).eps
    gate = 8 * n * eps * (1 if cond is None else np.sqrt(cond))
    assert resid < gate, (resid, gate)
