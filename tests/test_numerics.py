"""Numerics observability tests (ISSUE 10).

The acceptance surface of the Option.NumMonitor in-carry gauge layer
(obs/numerics.py + the threaded mesh k-loops), the distributed
Hager-Higham condition estimators (dist_aux), the mixed ladder's
health-aware entry-tier routing (dist_refine), and the num.* reporting
surface:

- NumMonitor=off is jaxpr-IDENTICAL to the unmonitored kernels for
  every threaded k-loop, and monitoring ON changes neither the results
  (bitwise) nor the comm-audit wire bytes (the gauges ride the carry).
- Seeded adversarial inputs (utils.testing: Wilkinson growth,
  prescribed-spectrum ill-conditioned, near-singular-diagonal SPD) trip
  the gauges at their CLOSED-FORM values, depth-invariantly.
- The distributed condest matches the single-chip estimators to rtol
  and is bitwise-invariant across Option.BcastImpl.
- MixedPrecision=auto under monitoring routes pathological inputs
  straight to the GMRES tier (num.routed_gmres; the IR tier never runs)
  and still meets the residual gate.
- The refinement trajectory lands in the registry/report surface.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from slate_tpu.obs import REGISTRY, numerics
from slate_tpu.parallel import make_mesh
from slate_tpu.parallel.comm import comm_audit, use_bcast_impl
from slate_tpu.parallel.dist import from_dense
from slate_tpu.parallel.dist_aux import gecondest_dist, norm_dist, pocondest_dist
from slate_tpu.parallel.dist_chol import potrf_dist
from slate_tpu.parallel.dist_lu import (
    getrf_nopiv_dist,
    getrf_pp_dist,
    getrf_tntpiv_dist,
)
from slate_tpu.types import Norm, Option, Uplo
from slate_tpu.utils.testing import generate

from conftest import cpu_devices

N, NB = 48, 8


def mesh24():
    return make_mesh(2, 4, devices=cpu_devices(8))


def _dist(a, mesh, pad=True):
    return from_dense(jnp.asarray(a), mesh, NB, diag_pad_one=pad)


def _factor_cases(mesh):
    """(name, fn(num_monitor), tiles-extractor) per threaded factor loop."""
    spd = generate("spd", N, seed=0)
    dom = generate("dominant", N, seed=1)
    gen = generate("randn", N, seed=2) + N * np.eye(N)
    return [
        ("potrf", lambda nm=None, la=None: potrf_dist(
            _dist(spd, mesh), lookahead=la, num_monitor=nm)),
        ("getrf_nopiv", lambda nm=None, la=None: getrf_nopiv_dist(
            _dist(np.tril(dom) + N * np.eye(N), mesh), lookahead=la,
            num_monitor=nm)),
        ("getrf_pp", lambda nm=None, la=None: getrf_pp_dist(
            _dist(gen, mesh), lookahead=la, num_monitor=nm)),
        ("getrf_tntpiv", lambda nm=None, la=None: getrf_tntpiv_dist(
            _dist(gen, mesh), lookahead=la, num_monitor=nm)),
    ]


# ---------------------------------------------------------------------------
# off-mode identity + monitored bitwise equality + wire-byte invariance
# ---------------------------------------------------------------------------


def test_monitoring_adds_zero_audited_wire_bytes():
    """The acceptance bound: gauges ride the carry, not the network —
    the audited comm-byte totals are IDENTICAL with monitoring on.

    Runs FIRST in this module (pytest executes in definition order) with
    a single cache clear, so every off/on kernel traces fresh inside its
    audit exactly once and later tests reuse the compiled programs."""
    mesh = mesh24()
    jax.clear_caches()
    for name, fn in _factor_cases(mesh):
        with comm_audit() as off_recs:
            fn(nm="off")
        # nm=on is a distinct static-arg variant: first trace, fresh records
        with comm_audit() as on_recs:
            fn(nm="on")
        off_total = sum(b * m for _, b, m in off_recs)
        on_total = sum(b * m for _, b, m in on_recs)
        assert off_total == on_total, (
            f"{name}: monitored kernel moved {on_total - off_total} extra "
            "audited bytes")


def test_off_is_jaxpr_identical_per_kernel():
    """NumMonitor=off must trace the exact unmonitored jaxpr for every
    threaded k-loop (and auto must resolve to off while obs is
    disabled)."""
    mesh = mesh24()
    for name, fn in _factor_cases(mesh):
        j_off = jax.make_jaxpr(lambda _=None, fn=fn: fn(nm="off"))()
        j_def = jax.make_jaxpr(lambda _=None, fn=fn: fn())()
        assert str(j_off) == str(j_def), f"{name}: off != default jaxpr"
        j_on = jax.make_jaxpr(lambda _=None, fn=fn: fn(nm="on"))()
        assert str(j_on) != str(j_off), f"{name}: on traced no gauges"


def test_qr_he2hb_off_jaxpr_identical_and_zero_extra_bytes():
    """ISSUE 15: the FUSED geqrf loop and the he2hb (eig-chain) loop
    under NumMonitor — off is jaxpr-IDENTICAL to the default trace, on
    adds the in-carry gauge but ZERO extra audited wire bytes (pure
    make_jaxpr traces: no compiles, no cache clears)."""
    from slate_tpu.parallel.dist_qr import geqrf_dist
    from slate_tpu.parallel.dist_twostage import he2hb_dist

    mesh = mesh24()
    gen = _dist(generate("randn", N, seed=12), mesh, pad=False)
    spd = _dist(generate("spd", N, seed=13), mesh, pad=False)
    cases = [
        ("geqrf", gen, lambda d, nm: geqrf_dist(d, num_monitor=nm)),
        ("he2hb", spd, lambda d, nm: he2hb_dist(d, num_monitor=nm)),
    ]
    for name, d, fn in cases:
        with comm_audit() as off_recs:
            j_off = jax.make_jaxpr(
                lambda t, d=d, fn=fn: fn(_rewrap(t, d), "off"))(d.tiles)
        j_def = jax.make_jaxpr(
            lambda t, d=d, fn=fn: fn(_rewrap(t, d), None))(d.tiles)
        assert str(j_off) == str(j_def), f"{name}: off != default jaxpr"
        with comm_audit() as on_recs:
            j_on = jax.make_jaxpr(
                lambda t, d=d, fn=fn: fn(_rewrap(t, d), "on"))(d.tiles)
        assert str(j_on) != str(j_off), f"{name}: on traced no gauges"
        off_total = sum(b * m for _, b, m in off_recs)
        on_total = sum(b * m for _, b, m in on_recs)
        assert off_total == on_total > 0, (
            f"{name}: monitored loop moved {on_total - off_total} extra "
            "audited bytes")


def _rewrap(tiles, like):
    from slate_tpu.parallel.dist import DistMatrix

    return DistMatrix(tiles=tiles, m=like.m, n=like.n, nb=like.nb,
                      mesh=like.mesh, diag_pad=like.diag_pad)


def test_he2hb_monitored_bitwise_and_gauge_recorded():
    """The first eig-chain gauge (ISSUE 15): monitoring the fused he2hb
    loop changes no result bit, and the replicated panel-QR loss proxy
    lands as num.he2hb_orth_margin / he2hb_orth_loss_max (eps-class for
    a healthy operand)."""
    from slate_tpu.parallel.dist_twostage import he2hb_dist

    mesh = mesh24()
    spd = _dist(generate("spd", N, seed=13), mesh, pad=False)
    off = he2hb_dist(spd, num_monitor="off")
    numerics.reset()
    on = he2hb_dist(spd, num_monitor="on")
    for a, b in ((off.band.tiles, on.band.tiles), (off.vq, on.vq),
                 (off.tq, on.tq)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    vals = numerics.num_counter_values()
    assert 0.0 < vals["he2hb_orth_loss_max"] < 1e-10
    assert numerics.last_gauges("he2hb")["he2hb_orth_loss"] \
        == vals["he2hb_orth_loss_max"]
    assert any(g["name"] == "num.he2hb_orth_margin"
               for g in REGISTRY.snapshot().get("gauges", []))
    numerics.reset()


def test_mixed_refine_off_is_jaxpr_identical(rng):
    """The fused refinement program: NumMonitor=off == no option (the
    history buffer only ever enters the carry under on)."""
    from slate_tpu.parallel.dist_refine import posv_mixed_mesh

    mesh = mesh24()
    a = jnp.asarray(generate("spd", N, seed=3))
    b = jnp.asarray(rng.standard_normal((N, 2)))
    j_off = jax.make_jaxpr(lambda x, y: posv_mixed_mesh(
        x, y, mesh, NB, opts={Option.NumMonitor: "off"}))(a, b)
    j_def = jax.make_jaxpr(lambda x, y: posv_mixed_mesh(x, y, mesh, NB))(a, b)
    assert str(j_off) == str(j_def)
    j_on = jax.make_jaxpr(lambda x, y: posv_mixed_mesh(
        x, y, mesh, NB, opts={Option.NumMonitor: "on"}))(a, b)
    assert str(j_on) != str(j_off)


def test_monitored_results_bitwise_and_gauges_recorded():
    mesh = mesh24()
    for name, fn in _factor_cases(mesh):
        off = fn(nm="off")
        on = fn(nm="on")
        t_off = off[0].tiles if isinstance(off, tuple) else off.tiles
        t_on = on[0].tiles if isinstance(on, tuple) else on.tiles
        assert bool(jnp.all(t_off == t_on)), f"{name}: monitoring moved bits"
        assert numerics.last_gauges(name), f"{name}: no gauges recorded"


# ---------------------------------------------------------------------------
# gauge trips on the adversarial generators
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("la", [0, 2])
def test_wilkinson_growth_exact_and_depth_invariant(la):
    """The Wilkinson matrix realizes the 2^{n-1} partial-pivot growth
    bound exactly; the in-carry gauge reproduces it at every lookahead
    depth (panel-entry samples are strict-schedule intermediates)."""
    mesh = mesh24()
    w = _dist(generate("wilkinson", N), mesh)
    alarms0 = REGISTRY.counter_value("num.growth_alarms", op="getrf_pp")
    _lu, _perm, info = getrf_pp_dist(w, lookahead=la, num_monitor="on")
    assert int(info) == 0
    g = numerics.last_gauges("getrf_pp")
    assert g["growth"] == 2.0 ** (N - 1)
    assert g["growth"] > numerics.GROWTH_THRESHOLD
    assert REGISTRY.counter_value(
        "num.growth_alarms", op="getrf_pp") == alarms0 + 1


def test_nopiv_growth_gauge_benign_and_wilkinson():
    mesh = mesh24()
    d = _dist(generate("dominant", N, seed=4), mesh)
    getrf_nopiv_dist(d, num_monitor="on")
    assert numerics.last_gauges("getrf_nopiv")["growth"] < 4.0
    # Wilkinson needs no pivoting (unit diagonal pivots), so the nopiv
    # elimination realizes the same 2^{n-1} growth
    w = _dist(generate("wilkinson", N), mesh)
    _lu, info = getrf_nopiv_dist(w, num_monitor="on")
    assert int(info) == 0
    assert numerics.last_gauges("getrf_nopiv")["growth"] == 2.0 ** (N - 1)


def test_chol_margin_near_breakdown_seeded():
    """The planted 1/cond Schur pivot is exactly what the margin gauge
    reads; info stays 0 (the breakdown the info code CANNOT see)."""
    mesh = mesh24()
    near = _dist(generate("spd_neardiag", N, seed=5, cond=1e8), mesh)
    _l, info = potrf_dist(near, num_monitor="on")
    assert int(info) == 0
    g = numerics.last_gauges("potrf")
    assert g["margin"] == pytest.approx(1e-8, rel=1e-3)
    assert g["diag_min"] == pytest.approx(1e-4, rel=1e-3)
    well = _dist(generate("spd", N, seed=6), mesh)
    potrf_dist(well, num_monitor="on")
    assert numerics.last_gauges("potrf")["margin"] > 0.5


def test_chol_margin_depth_invariant():
    # strict depth 0 vs the default depth 1 the seeded test above ran
    mesh = mesh24()
    near = _dist(generate("spd_neardiag", N, seed=5, cond=1e8), mesh)
    potrf_dist(near, lookahead=0, num_monitor="on")
    assert numerics.last_gauges("potrf")["margin"] == pytest.approx(
        1e-8, rel=1e-3)


# ---------------------------------------------------------------------------
# distributed condest vs single-chip, across BcastImpl
# ---------------------------------------------------------------------------


def test_gecondest_dist_matches_single_chip():
    from slate_tpu.linalg.lu import getrf_array
    from slate_tpu.linalg.norms import gecondest
    from slate_tpu.ops.tile_ops import genorm

    mesh = mesh24()
    a = generate("svd", N, seed=7, cond=1e6)
    lu, perm, info = getrf_pp_dist(_dist(a, mesh), )
    assert int(info) == 0
    anorm = norm_dist(Norm.One, from_dense(jnp.asarray(a), mesh, NB))
    rc_d = float(gecondest_dist(lu, perm, anorm))
    rc_s = float(gecondest(Norm.One, getrf_array(jnp.asarray(a)),
                           genorm(Norm.One, jnp.asarray(a))))
    assert rc_d == pytest.approx(rc_s, rel=1e-6)
    # the estimate brackets the true conditioning (Hager-Higham is a
    # lower bound on ||A^-1||, so rcond is an over-estimate of rcond_true
    # by at most a small factor)
    assert 1e-8 < rc_d < 1e-4


def test_pocondest_dist_matches_single_chip_and_impl_bitwise():
    from slate_tpu.linalg.chol import potrf_array
    from slate_tpu.linalg.norms import pocondest
    from slate_tpu.ops.tile_ops import genorm

    mesh = mesh24()
    a = generate("spd_svd", N, seed=8, cond=1e5)
    l, info = potrf_dist(_dist(a, mesh))
    assert int(info) == 0
    anorm = norm_dist(Norm.One, from_dense(jnp.asarray(a), mesh, NB))
    rc = {}
    for impl in ("psum", "ring", "doubling"):
        with use_bcast_impl(impl):
            rc[impl] = float(pocondest_dist(l, anorm))
    assert rc["psum"] == rc["ring"] == rc["doubling"]
    f, _ = potrf_array(jnp.asarray(a), Uplo.Lower)
    rc_s = float(pocondest(Norm.One, f, genorm(Norm.One, jnp.asarray(a))))
    assert rc["ring"] == pytest.approx(rc_s, rel=1e-6)


# ---------------------------------------------------------------------------
# resolution chain
# ---------------------------------------------------------------------------


def test_num_monitor_resolution_chain(monkeypatch):
    from slate_tpu import obs

    monkeypatch.delenv(numerics.NUM_ENV, raising=False)
    assert numerics.resolve_num_monitor("on") == "on"
    assert numerics.resolve_num_monitor("off") == "off"
    # auto: off while obs is disabled, on when enabled
    assert numerics.resolve_num_monitor(None) == "off"
    with obs.force_enabled():
        assert numerics.resolve_num_monitor(None) == "on"
    # context beats env beats auto; explicit beats context
    monkeypatch.setenv(numerics.NUM_ENV, "on")
    assert numerics.resolve_num_monitor(None) == "on"
    with numerics.use_num_monitor("off"):
        assert numerics.resolve_num_monitor(None) == "off"
        assert numerics.resolve_num_monitor("on") == "on"
    with pytest.raises(ValueError):
        numerics.resolve_num_monitor("sometimes")


# ---------------------------------------------------------------------------
# IR trajectory + health-aware routing
# ---------------------------------------------------------------------------


def test_ir_history_exported_for_monitored_solve(rng):
    from slate_tpu.parallel.dist_refine import posv_mixed_mesh

    mesh = mesh24()
    a = jnp.asarray(generate("spd", N, seed=9))
    b = jnp.asarray(rng.standard_normal((N, 2)))
    x, iters, info = posv_mixed_mesh(
        a, b, mesh, NB, opts={Option.NumMonitor: "on"})
    assert int(info) == 0 and int(iters) >= 0
    hist = numerics.last_history("posv")
    # initial solve + one row per correction step
    assert len(hist) == int(iters) + 1
    rnorms = [h[0] for h in hist]
    assert all(np.isfinite(rnorms))
    if len(rnorms) >= 2:
        assert rnorms[-1] < rnorms[0]
    # the gauge series lands in the registry (the RunReport surface)
    snap = REGISTRY.snapshot()
    series = [g for g in snap["gauges"]
              if g["name"] == "ir.residual_history"
              and g["tags"].get("op") == "posv"]
    assert len(series) >= len(hist)


@pytest.mark.slow  # tier-1 budget relief (ISSUE 11): consistency
# check, not a per-kernel identity gate; ci/run_ci.sh's full pytest
# pass still runs it
def test_health_routing_skips_ir_to_gmres(rng):
    """cond 1e8 >> CONDEST_THRESHOLD: the monitored auto ladder must
    measure it on the f32 factor, skip the IR tier entirely, and still
    deliver an answer at the residual gate via GMRES-IR."""
    from slate_tpu.parallel.drivers import gesv_mesh

    mesh = mesh24()
    # N=96/nb=16 matches test_mixed_mesh's ladder shapes, so the heavy
    # GMRES/IR programs are jit-cache hits from the earlier module
    M, nb = 96, 16
    a = generate("svd", M, seed=10, cond=1e8)
    b = rng.standard_normal((M, 2))
    routed0 = REGISTRY.counter_value("num.routed_gmres", op="gesv")
    ir0 = REGISTRY.counter_value("ir.solves", op="gesv")
    esc0 = REGISTRY.counter_value("ir.escalated_gmres", op="gesv")
    with numerics.use_num_monitor("on"):
        x, info = gesv_mesh(jnp.asarray(a), jnp.asarray(b), mesh, nb)
    assert int(info) == 0
    assert REGISTRY.counter_value("num.routed_gmres", op="gesv") == routed0 + 1
    # IR tier skipped: no ir solve ran, and the route is NOT an escalation
    assert REGISTRY.counter_value("ir.solves", op="gesv") == ir0
    assert REGISTRY.counter_value("ir.escalated_gmres", op="gesv") == esc0
    assert numerics.last_gauges("gesv")["cond"] > numerics.CONDEST_THRESHOLD
    r = b - a @ np.asarray(x)
    eps = np.finfo(np.float64).eps
    gate = (np.abs(a).sum(axis=1).max() * np.abs(np.asarray(x)).max()
            * eps * np.sqrt(M) * 10)
    assert np.abs(r).max() <= gate


def test_unmonitored_ladder_unchanged(rng):
    """Without monitoring the ladder keeps the pre-ISSUE-10 behavior:
    the IR tier RUNS (the health route never fires, no condest is
    measured) — the exact contrast with the monitored test above, which
    skipped it on the same input."""
    from slate_tpu.parallel.drivers import gesv_mesh

    mesh = mesh24()
    M, nb = 96, 16  # shared ladder shapes (see the monitored test above)
    a = generate("svd", M, seed=10, cond=1e8)
    b = rng.standard_normal((M, 2))
    routed0 = REGISTRY.counter_value("num.routed_gmres", op="gesv")
    ir0 = REGISTRY.counter_value("ir.solves", op="gesv")
    x, info = gesv_mesh(jnp.asarray(a), jnp.asarray(b), mesh, nb)
    assert int(info) == 0
    assert REGISTRY.counter_value("num.routed_gmres", op="gesv") == routed0
    assert REGISTRY.counter_value("ir.solves", op="gesv") == ir0 + 1


# ---------------------------------------------------------------------------
# generators + reporting surface
# ---------------------------------------------------------------------------


def test_adversarial_generators_properties():
    w = generate("wilkinson", 16)
    assert np.all(np.diag(w) == 1) and w[-1, 0] == -1 and np.all(w[:, -1] == 1)
    s = generate("spd_svd", 32, cond=1e6)
    ev = np.linalg.eigvalsh(s)
    assert ev.min() > 0
    assert ev.max() / ev.min() == pytest.approx(1e6, rel=1e-3)
    nd = generate("spd_neardiag", 32, cond=1e8)
    ev2 = np.linalg.eigvalsh(nd)
    assert ev2.min() == pytest.approx(1e-8, rel=1e-3)


def test_num_section_in_report_and_gating():
    from slate_tpu.obs import report

    numerics.reset()
    numerics.record_lu_growth("getrf_pp", 1.0, 3.0)
    rep = report.make_report("num_test")
    assert rep["num"]["lu_growth_max"] == 3.0
    vals = report.load_values(rep)
    assert vals["num_lu_growth_max"] == 3.0
    # growth rising beyond threshold is a FAIL (lower-is-better)
    worse = dict(vals, num_lu_growth_max=12.0)
    failures, compared = report.check_regression(worse, vals, threshold=2.0)
    assert any("num_lu_growth_max" in f for f in failures)
    # an all-zero num section stays out of the comparison surface
    numerics.reset()
    rep0 = report.make_report("num_zero")
    assert not any(k.startswith("num_") for k in report.load_values(rep0))
    # sectioned-inconclusive vs artifacts that predate the num section
    assert "num_lu_growth_max" in report.inconclusive_keys(vals, {})


def test_numerics_perfetto_counter_track():
    from slate_tpu.obs import perfetto

    hist = [(1.0, 1.0), (1e-8, 1.0), (1e-16, 1.0)]
    evs = perfetto.numerics_counter_events(hist, op="gesv")
    assert sum(e["name"] == "num.ir_rnorm[gesv]" for e in evs) == 3
    trace = perfetto.chrome_trace()
    trace["traceEvents"].extend(evs)
    assert perfetto.validate_chrome_trace(trace) == []


def test_route_entry_tier_thresholds():
    assert numerics.route_entry_tier("gesv", {"growth": 2.0**30}, None)
    assert not numerics.route_entry_tier("gesv", {"growth": 2.0}, None)
    assert numerics.route_entry_tier("gesv", {}, 1e-9)
    assert not numerics.route_entry_tier("gesv", {}, 1e-3)
    # SPD near-breakdown: tiny margin relative to the diag scale
    assert numerics.route_entry_tier(
        "posv", {"margin": 1e-9, "diag_max": 1.0}, None)
    assert not numerics.route_entry_tier(
        "posv", {"margin": 0.5, "diag_max": 1.0}, None)
