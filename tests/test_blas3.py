"""BLAS-3 driver tests — residual-style checks mirroring test/test_gemm.cc,
test_trsm.cc, test_herk.cc etc. (reference test strategy SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.blas3 import trsm_array, trmm_array
from slate_tpu.types import Diag, Op, Side, Uplo
from slate_tpu.utils.testing import generate


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_gemm(dtype):
    a = generate("rands", 37, 23, dtype, seed=1)
    b = generate("rands", 23, 41, dtype, seed=2)
    c = generate("rands", 37, 41, dtype, seed=3)
    out = st.gemm(2.0, st.Matrix.from_array(a), st.Matrix.from_array(b), 0.5, st.Matrix.from_array(c))
    np.testing.assert_allclose(np.asarray(out.array), 2 * a @ b + 0.5 * c, rtol=1e-12, atol=1e-12)


def test_gemm_transposed_views():
    a = generate("rands", 23, 37, np.float64, seed=1)
    b = generate("rands", 41, 23, np.float64, seed=2)
    c = np.zeros((37, 41))
    at = st.Matrix.from_array(a).transposed()
    bt = st.Matrix.from_array(b).transposed()
    out = st.gemm(1.0, at, bt, 0.0, st.Matrix.from_array(c))
    np.testing.assert_allclose(np.asarray(out.array), a.T @ b.T, rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
def test_hemm(uplo):
    a = generate("hermitian", 20, dtype=np.complex128, seed=4)
    astore = np.tril(a) if uplo == Uplo.Lower else np.triu(a)
    b = generate("rands", 20, 15, np.complex128, seed=5)
    c = generate("rands", 20, 15, np.complex128, seed=6)
    am = st.HermitianMatrix.from_array(astore, uplo)
    out = st.hemm(Side.Left, 1.5, am, st.Matrix.from_array(b), 0.5, st.Matrix.from_array(c))
    np.testing.assert_allclose(np.asarray(out.array), 1.5 * a @ b + 0.5 * c, rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
def test_herk(uplo):
    a = generate("rands", 18, 9, np.complex128, seed=7)
    c0 = generate("hermitian", 18, dtype=np.complex128, seed=8)
    cstore = np.tril(c0) if uplo == Uplo.Lower else np.triu(c0)
    cm = st.HermitianMatrix.from_array(cstore, uplo)
    out = st.herk(2.0, st.Matrix.from_array(a), 3.0, cm)
    expect = 2 * a @ a.conj().T + 3 * c0
    got = np.asarray(out.full)
    np.testing.assert_allclose(got, expect, rtol=1e-12, atol=1e-12)


def test_syrk_syr2k():
    a = generate("rands", 12, 7, np.float64, seed=9)
    b = generate("rands", 12, 7, np.float64, seed=10)
    c0 = generate("rands", 12, 12, np.float64, seed=11)
    c0 = (c0 + c0.T) / 2
    cm = st.SymmetricMatrix.from_array(np.tril(c0), Uplo.Lower)
    out = st.syrk(1.0, st.Matrix.from_array(a), 2.0, cm)
    np.testing.assert_allclose(np.asarray(out.full), a @ a.T + 2 * c0, rtol=1e-12, atol=1e-12)
    out2 = st.syr2k(1.0, st.Matrix.from_array(a), st.Matrix.from_array(b), 0.0, cm)
    np.testing.assert_allclose(np.asarray(out2.full), a @ b.T + b @ a.T, rtol=1e-12, atol=1e-12)


def test_her2k():
    a = generate("rands", 10, 6, np.complex128, seed=12)
    b = generate("rands", 10, 6, np.complex128, seed=13)
    c0 = generate("hermitian", 10, dtype=np.complex128, seed=14)
    cm = st.HermitianMatrix.from_array(np.tril(c0), Uplo.Lower)
    alpha = 1.0 + 2.0j
    out = st.her2k(alpha, st.Matrix.from_array(a), st.Matrix.from_array(b), 1.0, cm)
    expect = alpha * a @ b.conj().T + np.conj(alpha) * b @ a.conj().T + c0
    np.testing.assert_allclose(np.asarray(out.full), expect, rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("side", [Side.Left, Side.Right])
@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
@pytest.mark.parametrize("op", [Op.NoTrans, Op.Trans, Op.ConjTrans])
@pytest.mark.parametrize("diag", [Diag.NonUnit, Diag.Unit])
def test_trsm_all_variants(side, uplo, op, diag):
    n = 35
    rng = np.random.default_rng(15)
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    bshape = (n, 13) if side == Side.Left else (13, n)
    b = rng.standard_normal(bshape)
    x = np.asarray(trsm_array(side, uplo, op, diag, 2.0, jnp.asarray(a), jnp.asarray(b)))
    t = np.tril(a) if uplo == Uplo.Lower else np.triu(a)
    if diag == Diag.Unit:
        np.fill_diagonal(t, 1.0)
    opa = {Op.NoTrans: t, Op.Trans: t.T, Op.ConjTrans: t.conj().T}[op]
    resid = opa @ x - 2 * b if side == Side.Left else x @ opa - 2 * b
    denom = np.abs(opa).sum() * np.abs(x).sum() + np.abs(b).sum()
    assert np.abs(resid).max() / denom < 1e-13


def test_trsm_large_recursive():
    # exercise the recursive path (n > _NB) with well-conditioned triangle
    n = 700
    rng = np.random.default_rng(16)
    a = np.tril(rng.standard_normal((n, n)) / np.sqrt(n)) + 2 * np.eye(n)
    b = rng.standard_normal((n, 3))
    x = np.asarray(trsm_array(Side.Left, Uplo.Lower, Op.NoTrans, Diag.NonUnit, 1.0, jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(np.tril(a) @ x, b, atol=1e-10)


@pytest.mark.parametrize("side", [Side.Left, Side.Right])
def test_trmm(side):
    n, k = 21, 9
    rng = np.random.default_rng(17)
    a = rng.standard_normal((n, n))
    b = rng.standard_normal((n, k) if side == Side.Left else (k, n))
    out = np.asarray(trmm_array(side, Uplo.Upper, Op.NoTrans, Diag.NonUnit, 3.0, jnp.asarray(a), jnp.asarray(b)))
    expect = 3 * np.triu(a) @ b if side == Side.Left else 3 * b @ np.triu(a)
    np.testing.assert_allclose(out, expect, rtol=1e-12, atol=1e-12)


def test_gbmm():
    m, k, n = 16, 16, 10
    rng = np.random.default_rng(18)
    a = rng.standard_normal((m, k))
    kl, ku = 2, 3
    band = np.zeros_like(a)
    for i in range(m):
        for j in range(k):
            if -kl <= j - i <= ku:
                band[i, j] = a[i, j]
    b = rng.standard_normal((k, n))
    am = st.BandMatrix.from_array(a, kl, ku)
    out = st.gbmm(1.0, am, st.Matrix.from_array(b), 0.0, st.Matrix.from_array(np.zeros((m, n))))
    np.testing.assert_allclose(np.asarray(out.array), band @ b, rtol=1e-12, atol=1e-12)


def test_tbsm_with_pivots():
    n = 12
    rng = np.random.default_rng(19)
    a = np.tril(rng.standard_normal((n, n))) + 4 * np.eye(n)
    b = rng.standard_normal((n, 4))
    piv = np.arange(n)
    piv[0], piv[5] = 5, 5  # swap rows 0<->5 at step 0
    am = st.TriangularMatrix.from_array(a, Uplo.Lower)
    out = st.tbsm(Side.Left, 1.0, am, st.Matrix.from_array(b), pivots=jnp.asarray(piv))
    bp = b.copy()
    bp[[0, 5]] = bp[[5, 0]]
    np.testing.assert_allclose(np.asarray(np.tril(a) @ out.array), bp, atol=1e-12)
