"""LU family tests — backward-error gates mirroring test/test_gesv.cc,
test_getri.cc, test_gbsv.cc; pivot-growth checks for tntpiv/nopiv/rbt."""

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.linalg import (
    gbsv_array,
    gesv_array,
    gesv_mixed_array,
    gesv_mixed_gmres_array,
    gesv_rbt_array,
    getrf_array,
    getrf_nopiv_array,
    getrf_tntpiv_array,
    getri_array,
    getrs_array,
)
from slate_tpu.types import MethodLU, Op
from slate_tpu.utils.testing import generate


def _check_lu(a, f, rtol=1e-13):
    lu, perm = np.asarray(f.lu), np.asarray(f.perm)
    n = min(a.shape)
    l = np.tril(lu, -1)[:, :n] + np.eye(a.shape[0], n)
    u = np.triu(lu)[:n]
    pa = a[perm]
    resid = np.abs(l @ u - pa).max()
    assert resid / (np.abs(a).max() * n) < rtol, resid


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_getrf(dtype):
    a = generate("rands", 90, 90, dtype, seed=1)
    f = getrf_array(jnp.asarray(a))
    assert int(f.info) == 0
    _check_lu(a, f)
    # partial pivoting: |L| <= 1
    assert np.abs(np.tril(np.asarray(f.lu), -1)).max() <= 1 + 1e-12


def test_getrf_rectangular():
    a = generate("rands", 120, 70, np.float64, seed=2)
    f = getrf_array(jnp.asarray(a))
    _check_lu(a, f)


def test_getrf_large():
    a = generate("rands", 500, 500, np.float64, seed=3)
    f = getrf_array(jnp.asarray(a))
    _check_lu(a, f)


def test_gesv():
    n, nrhs = 100, 4
    a = generate("rands", n, n, np.float64, seed=4)
    b = generate("rands", n, nrhs, np.float64, seed=5)
    x, f = gesv_array(jnp.asarray(a), jnp.asarray(b))
    resid = np.abs(a @ np.asarray(x) - b).max()
    assert resid / (np.abs(a).sum(1).max() * np.abs(x).max()) < 1e-13


def test_getrs_trans():
    n = 50
    a = generate("rands", n, n, np.complex128, seed=6)
    b = generate("rands", n, 2, np.complex128, seed=7)
    f = getrf_array(jnp.asarray(a))
    xt = getrs_array(f, jnp.asarray(b), Op.Trans)
    np.testing.assert_allclose(a.T @ np.asarray(xt), b, atol=1e-10)
    xh = getrs_array(f, jnp.asarray(b), Op.ConjTrans)
    np.testing.assert_allclose(a.conj().T @ np.asarray(xh), b, atol=1e-10)


def test_getrf_nopiv():
    a = generate("dominant", 80, 80, np.float64, seed=8)
    f = getrf_nopiv_array(jnp.asarray(a))
    lu = np.asarray(f.lu)
    l = np.tril(lu, -1) + np.eye(80)
    u = np.triu(lu)
    assert np.abs(l @ u - a).max() / np.abs(a).max() < 1e-12


def test_getrf_tntpiv():
    a = generate("rands", 200, 200, np.float64, seed=9)
    f = getrf_tntpiv_array(jnp.asarray(a))
    assert int(f.info) == 0
    _check_lu(a, f, rtol=1e-11)  # tournament: bounded but larger growth
    x = getrs_array(f, jnp.asarray(generate("rands", 200, 1, np.float64, seed=10)))
    assert np.isfinite(np.asarray(x)).all()


def test_getri():
    n = 60
    a = generate("rands", n, n, np.float64, seed=11)
    f = getrf_array(jnp.asarray(a))
    inv = np.asarray(getri_array(f))
    np.testing.assert_allclose(inv @ a, np.eye(n), atol=1e-10)


def test_gesv_rbt():
    n = 64
    a = generate("rands", n, n, np.float64, seed=12) + 2 * np.eye(n)
    b = generate("rands", n, 1, np.float64, seed=13)
    x, f = gesv_rbt_array(jnp.asarray(a), jnp.asarray(b))
    resid = np.abs(a @ np.asarray(x) - b).max()
    assert resid / np.abs(b).max() < 1e-10


def test_gesv_rbt_nonpow2():
    n = 50  # padding path
    a = generate("rands", n, n, np.float64, seed=14) + 2 * np.eye(n)
    b = generate("rands", n, 2, np.float64, seed=15)
    x, f = gesv_rbt_array(jnp.asarray(a), jnp.asarray(b))
    assert np.abs(a @ np.asarray(x) - b).max() / np.abs(b).max() < 1e-9


def test_gesv_mixed():
    n = 100
    a = generate("rands", n, n, np.float64, seed=16) + n * np.eye(n)
    b = generate("rands", n, 1, np.float64, seed=17)
    x, iters, done, info = gesv_mixed_array(jnp.asarray(a), jnp.asarray(b))
    assert bool(done)
    assert int(iters) >= 0
    assert np.abs(a @ np.asarray(x) - b).max() / np.abs(b).max() < 1e-12


def test_gesv_mixed_gmres():
    n = 80
    a = generate("rands", n, n, np.float64, seed=18) + n * np.eye(n)
    b = generate("rands", n, 1, np.float64, seed=19)[:, 0]
    x, rnorm = gesv_mixed_gmres_array(jnp.asarray(a), jnp.asarray(b))
    assert np.abs(a @ np.asarray(x) - b).max() / np.abs(b).max() < 1e-10


@pytest.mark.parametrize("dominant", [True, False])
def test_gbsv(dominant):
    # non-dominant case forces real pivoting: L multipliers scatter outside
    # the kl band and must NOT be projected away (review-found bug)
    n, kl, ku = 70, 3, 2
    rng = np.random.default_rng(20)
    ab = np.zeros((n, n))
    for d in range(-kl, ku + 1):
        ab += np.diag(rng.standard_normal(n - abs(d)), d)
    if dominant:
        ab += 10 * np.eye(n)
    b = rng.standard_normal((n, 2))
    x, f = gbsv_array(jnp.asarray(ab), jnp.asarray(b), kl, ku)
    resid = np.abs(ab @ np.asarray(x) - b).max()
    assert resid / (np.abs(ab).sum(1).max() * max(np.abs(x).max(), 1)) < 1e-12
    # U band stays within kl+ku
    u = np.triu(np.asarray(f.lu))
    assert np.abs(np.triu(u, kl + ku + 1)).max() == 0


def test_gesv_mixed_gmres_multirhs():
    n = 40
    a = generate("rands", n, n, np.float64, seed=21) + n * np.eye(n)
    b = generate("rands", n, 3, np.float64, seed=22)
    x, rnorm = gesv_mixed_gmres_array(jnp.asarray(a), jnp.asarray(b))
    assert np.asarray(x).shape == (n, 3)
    assert np.abs(a @ np.asarray(x) - b).max() / np.abs(b).max() < 1e-10


def test_getrf_wide():
    # m < n: only m elimination steps (review-found bug: looping w steps
    # corrupted row m-1 through clamped out-of-bounds swaps)
    m, n = 4, 8
    a = generate("rands", m, n, np.float64, seed=30)
    f = getrf_array(jnp.asarray(a))
    lu, perm = np.asarray(f.lu), np.asarray(f.perm)
    assert sorted(perm.tolist()) == list(range(m))  # a real permutation
    l = np.tril(lu[:, :m], -1) + np.eye(m)
    u = np.triu(lu)
    np.testing.assert_allclose(l @ u, a[perm], atol=1e-12)


def test_rbt_factors_reusable():
    # RBTFactors.solve must solve against the ORIGINAL A for fresh RHS
    n = 48
    a = generate("rands", n, n, np.float64, seed=31) + 2 * np.eye(n)
    b1 = generate("rands", n, 1, np.float64, seed=32)
    b2 = generate("rands", n, 2, np.float64, seed=33)
    x1, f = gesv_rbt_array(jnp.asarray(a), jnp.asarray(b1))
    assert int(f.info) == 0
    x2 = f.solve(jnp.asarray(b2))
    resid = np.abs(a @ np.asarray(x2) - b2).max() / np.abs(b2).max()
    assert resid < 1e-8


# ---------------------------------------------------------------------------
# Scanned (single-program) variants — north-star-size code paths
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape,nb", [((200, 200), 64), ((300, 100), 64),
                                      ((100, 300), 32), ((65, 130), 64)])
def test_getrf_scan_shapes(shape, nb):
    from slate_tpu.linalg.lu import getrf_scan_array

    a = generate("rands", *shape, np.float64, seed=7)
    f = getrf_scan_array(jnp.asarray(a), nb=nb)
    _check_lu(a, f)
    assert sorted(np.asarray(f.perm).tolist()) == list(range(shape[0]))


def test_getrf_scan_matches_recursive_pivots():
    from slate_tpu.linalg.lu import getrf_scan_array

    a = generate("rands", 96, 96, np.float64, seed=8)
    f1 = getrf_scan_array(jnp.asarray(a))
    f2 = getrf_array(jnp.asarray(a))
    assert (np.asarray(f1.perm) == np.asarray(f2.perm)).all()
    assert np.abs(np.asarray(f1.lu) - np.asarray(f2.lu)).max() < 1e-12


def test_getrf_scan_singular_info():
    from slate_tpu.linalg.lu import getrf_scan_array

    a = np.asarray(generate("rands", 64, 64, np.float64, seed=9)).copy()
    a[:, 10] = 0.0
    f = getrf_scan_array(jnp.asarray(a))
    assert int(f.info) == 11


def test_getrf_tntpiv_scan_solve():
    # non-diag-dominant solve through the scanned tournament path
    a = generate("rands", 130, 130, np.float64, seed=10)
    b = generate("rands", 130, 2, np.float64, seed=11)
    f = getrf_tntpiv_array(jnp.asarray(a), nb=32)
    _check_lu(a, f, rtol=1e-12)
    x = np.asarray(getrs_array(f, jnp.asarray(b)))
    assert np.abs(a @ x - b).max() / np.abs(a).max() < 1e-10


def test_getri_oop():
    from slate_tpu.linalg import getri_oop_array

    rng = np.random.default_rng(11)
    a = rng.standard_normal((96, 96)) + 6 * np.eye(96)
    ainv, info = getri_oop_array(jnp.asarray(a))
    assert int(info) == 0
    assert np.abs(a @ np.asarray(ainv) - np.eye(96)).max() < 1e-11


@pytest.mark.slow  # tier-1 budget relief (ISSUE 11): consistency
# check, not a per-kernel identity gate; ci/run_ci.sh's full pytest
# pass still runs it
def test_getrf_left_looking():
    # the f64 TPU path (getrf_array dispatches here on-chip at n >= 4096):
    # blocked forward-substitution U rows, big-k Schur gemm, all-gemm
    # recursive panel with fused unit-L inverses, history row permutes
    from slate_tpu.linalg.lu import _getrf_left_looking

    rng = np.random.default_rng(17)
    for n, nb in [(300, 96), (640, 256)]:
        a = rng.standard_normal((n, n))
        lu, perm = _getrf_left_looking(jnp.asarray(a), nb=nb)
        lu, perm = np.asarray(lu), np.asarray(perm)
        low = np.tril(lu, -1) + np.eye(n)
        up = np.triu(lu)
        resid = np.linalg.norm(a[perm] - low @ up) / np.linalg.norm(a)
        assert resid < 8 * n * np.finfo(np.float64).eps, (n, nb, resid)
        assert np.abs(np.tril(low, -1)).max() <= 1 + 1e-12  # partial pivoting
