"""HBM memory observability (ISSUE 9): MemoryModel vs measured
``memory_analysis`` temps for the mesh kernels, donation-alias
verification over the whole donation registry, lookahead residency
arithmetic, mem.* report schema + ``--check`` gating, zero-overhead
disabled mode (no live_arrays calls, jaxpr-identical drivers), OOM
forensics, the model-driven f64 potrf routing, and the Perfetto memory
counter track."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu import obs
from slate_tpu.obs import memmodel, memory, memwatch, perfetto, report
from tests.conftest import cpu_devices


def mesh24():
    from slate_tpu.parallel import make_mesh

    return make_mesh(2, 4, devices=cpu_devices(8))


def _case(op, n, nb, depth, impl, mesh):
    return memwatch._build_case(op, n, nb, mesh, depth, impl)


# ---------------------------------------------------------------------------
# model vs measured (the tentpole acceptance: within 10% on tier-1 shapes)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl", ["psum", "ring"])
@pytest.mark.parametrize("n,nb,depth", [(96, 8, 1), (192, 16, 0)])
@pytest.mark.parametrize("op", ["summa", "potrf", "getrf_nopiv"])
def test_model_matches_measured_temps(op, n, nb, depth, impl):
    mesh = mesh24()
    fn, args, _run = _case(op, n, nb, depth, impl, mesh)
    meas = memory.aot_memory_analysis(fn, *args)
    assert meas is not None and meas["temp_bytes"] > 0
    model = memmodel.MemoryModel(op, n, nb, (2, 4), "float32",
                                 lookahead=depth, bcast_impl=impl)
    err = abs(model.workspace_bytes - meas["temp_bytes"]) / meas["temp_bytes"]
    assert err <= memwatch.MODEL_TOL, (
        f"{op} n={n} nb={nb} d={depth} {impl}: model "
        f"{model.workspace_bytes:,.0f} vs measured {meas['temp_bytes']:,.0f} "
        f"({err:.1%})")
    # the exact terms: argument and output shards are tile arithmetic
    assert meas["arg_bytes"] == model.arg_bytes
    assert abs(meas["out_bytes"] - model.out_bytes) <= 64


def test_model_peak_is_arg_out_workspace():
    m = memmodel.MemoryModel("summa", 96, 8, (2, 4))
    assert m.peak_bytes == m.arg_bytes + m.out_bytes + m.workspace_bytes


@pytest.mark.parametrize("op", ["trsm", "geqrf", "he2hb"])
def test_issue15_op_models_match_measured(op):
    """ISSUE 15: trsm promoted to exact-class, geqrf/he2hb newly modeled
    (the QR/eig chains were the ROADMAP's last unmodeled drivers) — one
    engine-lowering point tier-1; the full two-point psum/ring sweep
    runs at -m slow.  Arg bytes are exact tile arithmetic; the
    multi-array out bytes (T_loc/tree and reflector/WY stacks) land
    within the measured assignment slack."""
    mesh = mesh24()
    fn, args, _run = _case(op, 96, 8, 1, "ring", mesh)
    meas = memory.aot_memory_analysis(fn, *args)
    assert meas is not None and meas["temp_bytes"] > 0
    model = memmodel.MemoryModel(op, 96, 8, (2, 4), "float32",
                                 lookahead=1, bcast_impl="ring")
    err = abs(model.workspace_bytes - meas["temp_bytes"]) / meas["temp_bytes"]
    assert err <= memwatch.MODEL_TOL, (
        f"{op}: model {model.workspace_bytes:,.0f} vs measured "
        f"{meas['temp_bytes']:,.0f} ({err:.1%})")
    assert meas["arg_bytes"] == model.arg_bytes
    assert abs(meas["out_bytes"] - model.out_bytes) <= 64


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["psum", "ring"])
@pytest.mark.parametrize("n,nb,depth", [(96, 8, 1), (192, 16, 0)])
@pytest.mark.parametrize("op", ["trsm", "geqrf", "he2hb"])
def test_issue15_op_models_full_sweep(op, n, nb, depth, impl):
    mesh = mesh24()
    fn, args, _run = _case(op, n, nb, depth, impl, mesh)
    meas = memory.aot_memory_analysis(fn, *args)
    model = memmodel.MemoryModel(op, n, nb, (2, 4), "float32",
                                 lookahead=depth, bcast_impl=impl)
    err = abs(model.workspace_bytes - meas["temp_bytes"]) / meas["temp_bytes"]
    assert err <= memwatch.MODEL_TOL, (
        f"{op} n={n} nb={nb} d={depth} {impl}: {err:.1%}")


def test_predict_max_n_answers_for_qr_eig():
    """ISSUE 15: the feasibility answer exists for the QR/eig family —
    and the he2hb reflector stacks make its admissible n strictly
    smaller than the tile-stack-only LU model at the same budget (the
    over-admission the Router mapping fixes)."""
    budget = 16 * 2**30
    for op in ("geqrf", "he2hb"):
        nmax = memmodel.predict_max_n(budget, op, nb=256, grid=(2, 4))
        assert nmax > 0
        m = memmodel.MemoryModel(op, nmax, 256, (2, 4))
        assert m.peak_bytes <= budget
        step = 256 * 4
        over = memmodel.MemoryModel(op, nmax + step, 256, (2, 4))
        assert over.peak_bytes > budget
    assert (memmodel.predict_max_n(budget, "he2hb", nb=256, grid=(2, 4))
            < memmodel.predict_max_n(budget, "getrf_nopiv", nb=256,
                                     grid=(2, 4)))


# ---------------------------------------------------------------------------
# lookahead residency: depth adds exactly d panel-payload buffers
# ---------------------------------------------------------------------------


def test_lookahead_adds_exactly_d_panel_buffers():
    base = memmodel.MemoryModel("summa", 192, 16, (2, 4), lookahead=0)
    for d in (1, 2, 3):
        m = memmodel.MemoryModel("summa", 192, 16, (2, 4), lookahead=d)
        assert m.workspace_bytes - base.workspace_bytes == d * m.payload_bytes
    # factor loops carry the deferred payload next to the fresh one and
    # cap at depth 1: +2 payload pairs at any depth >= 1
    b0 = memmodel.MemoryModel("potrf", 192, 16, (2, 4), lookahead=0)
    for d in (1, 3):
        m = memmodel.MemoryModel("potrf", 192, 16, (2, 4), lookahead=d)
        assert m.workspace_bytes - b0.workspace_bytes == 2 * m.payload_bytes


def test_la_live_buffers_single_source():
    from slate_tpu.parallel.comm import la_live_buffers

    assert la_live_buffers(0) == 1
    assert la_live_buffers(2) == 3
    assert la_live_buffers(0, factor_loop=True) == 1
    assert la_live_buffers(1, factor_loop=True) == 3
    assert la_live_buffers(5, factor_loop=True) == 3  # caps at depth 1


def test_ft_augmentation_grows_tile_grid():
    plain = memmodel.MemoryModel("potrf", 96, 8, (2, 4))
    ft = memmodel.MemoryModel("potrf", 96, 8, (2, 4), ft=True)
    assert ft.nt > plain.nt
    assert ft.arg_bytes > plain.arg_bytes


# ---------------------------------------------------------------------------
# donation verification: every registry entry must MEASURABLY alias
# ---------------------------------------------------------------------------


def test_every_donation_registry_entry_aliases():
    from slate_tpu.analysis import registry

    ctx = registry.make_ctx()
    assert registry.DONATIONS, "donation registry is empty"
    for name, spec in sorted(registry.DONATIONS.items()):
        fn, args, donate = spec.build(ctx)
        donated, aliased = memory.donation_alias_bytes(fn, args, donate)
        assert donated > 0, name
        assert aliased >= donated, (
            f"{name}: donated {donated:,.0f} B but only {aliased:,.0f} "
            "aliased in the compiled executable — the donation is lost")


def test_seeded_donation_loss_is_measurable():
    # the bug class the gate exists for: drop donate_argnums and the
    # measured alias bytes collapse to zero
    n = 128
    ap = jnp.asarray(np.random.default_rng(0).standard_normal((n, n)))
    fn = lambda x: x * 2.0 + 1.0  # noqa: E731
    donated, aliased = memory.donation_alias_bytes(fn, (ap,), (0,))
    assert aliased >= donated > 0
    donated2, aliased2 = memory.donation_alias_bytes(fn, (ap,), ())
    assert donated2 == 0 and aliased2 == 0.0


# ---------------------------------------------------------------------------
# mem report schema + --check gating
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mem_report():
    return memwatch.run_memwatch("summa", n=96, nb=8, depth=1,
                                 bcast_impl="ring", mesh=mesh24(),
                                 with_donations=False)


def test_mem_report_schema(mem_report):
    assert report.validate_report(mem_report) == []
    vals = mem_report["values"]
    for key in ("mem.arg_bytes", "mem.out_bytes", "mem.temp_bytes",
                "mem.alias_bytes", "mem.model_workspace_bytes",
                "mem.model_peak_bytes", "mem.model_err_frac"):
        assert key in vals, key
    assert vals["mem.temp_bytes"] > 0
    assert vals["mem.model_err_frac"] <= memwatch.MODEL_TOL


def test_mem_report_check_gating(mem_report, tmp_path):
    import copy

    good = tmp_path / "mem_good.json"
    good.write_text(json.dumps(mem_report))
    # unchanged passes (runtime keys ignored like CI does)
    rc = report.main(["--check", str(good), str(good),
                      "--ignore", "mem.*_runtime_*"])
    assert rc == 0
    # a 10x model error (the extra-copy bug class) fails the gate
    bad = copy.deepcopy(mem_report)
    bad["values"]["mem.model_err_frac"] = \
        max(0.5, 10 * bad["values"]["mem.model_err_frac"])
    bad["values"]["mem.temp_bytes"] *= 3.0
    bad_path = tmp_path / "mem_bad.json"
    bad_path.write_text(json.dumps(bad))
    rc = report.main(["--check", str(bad_path), str(good),
                      "--ignore", "mem.*_runtime_*"])
    assert rc == 1
    # runtime keys alone never gate: wildly different runtime peaks pass
    runtime = copy.deepcopy(mem_report)
    runtime["values"]["mem.summa_runtime_live_bytes"] = \
        runtime["values"].get("mem.summa_runtime_live_bytes", 1.0) * 1e6 + 1e9
    rt_path = tmp_path / "mem_rt.json"
    rt_path.write_text(json.dumps(runtime))
    rc = report.main(["--check", str(rt_path), str(good),
                      "--ignore", "mem.*_runtime_*"])
    assert rc == 0


def test_mem_section_rides_run_reports():
    obs.reset()
    with obs.force_enabled(), memory.force_sampling():
        with obs.driver_span("memsec_probe"):
            jnp.zeros((8, 8)).block_until_ready()
    rep = report.make_report("memsec")
    assert "mem" in rep and rep["mem"]["samples"] >= 1
    vals = report.load_values(rep)
    assert vals.get("mem_samples", 0) >= 1
    assert "mem_live_bytes_max" in vals
    obs.reset()


def test_mem_keys_are_sectioned_inconclusive_against_old_artifacts():
    new = {"mem.temp_bytes": 100.0, "x_gflops": 5.0}
    old = {"x_gflops": 5.0}
    keys = report.inconclusive_keys(new, old)
    assert keys == ["mem.temp_bytes"]


# ---------------------------------------------------------------------------
# disabled mode: zero overhead, jaxpr-identical
# ---------------------------------------------------------------------------


def test_disabled_mode_makes_no_live_array_calls():
    from slate_tpu.parallel import potrf_dist
    from slate_tpu.parallel.dist import from_dense

    obs.reset()
    assert not obs.enabled()
    mesh = mesh24()
    rng = np.random.default_rng(0)
    g = rng.standard_normal((64, 64))
    spd = jnp.asarray((g @ g.T / 64 + 2 * np.eye(64)).astype(np.float32))
    ad = from_dense(spd, mesh, 8, diag_pad_one=True)
    before = memory.LIVE_CALLS
    _, info = potrf_dist(ad)
    assert int(info) == 0
    assert memory.LIVE_CALLS == before


def test_disabled_instrumented_driver_is_jaxpr_identical():
    from slate_tpu.parallel import potrf_dist
    from slate_tpu.parallel.dist import from_dense

    assert not obs.enabled()
    mesh = mesh24()
    rng = np.random.default_rng(0)
    g = rng.standard_normal((64, 64))
    spd = jnp.asarray((g @ g.T / 64 + 2 * np.eye(64)).astype(np.float32))
    ad = from_dense(spd, mesh, 8, diag_pad_one=True)
    wrapped = jax.make_jaxpr(lambda d: potrf_dist(d))(ad)
    raw = jax.make_jaxpr(lambda d: potrf_dist.__wrapped__(d))(ad)
    assert str(wrapped) == str(raw)


def test_enabled_span_records_mem_sample():
    from slate_tpu.parallel import potrf_dist
    from slate_tpu.parallel.dist import from_dense

    obs.reset()
    mesh = mesh24()
    rng = np.random.default_rng(0)
    g = rng.standard_normal((64, 64))
    spd = jnp.asarray((g @ g.T / 64 + 2 * np.eye(64)).astype(np.float32))
    ad = from_dense(spd, mesh, 8, diag_pad_one=True)
    with obs.force_enabled(), memory.force_sampling():
        before = memory.LIVE_CALLS
        _, info = potrf_dist(ad)
        assert memory.LIVE_CALLS > before
    spans = [s for s in obs.FINISHED if s["name"] == "potrf_dist"]
    assert spans and spans[0]["metrics"].get("mem.live_bytes", 0) > 0
    assert memory.mem_counter_values()["samples"] >= 1
    obs.reset()


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------


def test_oom_detection_and_report_text():
    exc = RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                       "8589934592 bytes")
    assert memory.is_oom(exc)
    assert not memory.is_oom(ValueError("shape mismatch"))
    text = memory.oom_report_text("potrf_mesh", exc)
    assert "OOM forensics: potrf_mesh" in text
    assert "live buffers" in text or "live-buffer walk" in text
    assert "staged" in text  # the escape-route suggestions
    assert "Lookahead" in text
    assert "predict_max_n" in text
    # potrf drivers get the per-form predicted peaks
    assert "fused_ll" in text and "ozaki_cache" in text


def test_instrumented_driver_emits_oom_forensics(capsys):
    memory.reset()

    @obs.instrument("oom_probe")
    def boom():
        raise RuntimeError("RESOURCE_EXHAUSTED: out of HBM")

    assert not obs.enabled()  # forensics must fire even when obs is off
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        boom()
    assert memory.mem_counter_values()["oom_events"] == 1
    assert "OOM forensics: oom_probe" in capsys.readouterr().err
    memory.reset()


# ---------------------------------------------------------------------------
# feasibility + the model-driven f64 potrf routing (ISSUE 9 satellite)
# ---------------------------------------------------------------------------


def test_predict_max_n_respects_budget():
    budget = 16 * 2**30
    nmax = memmodel.predict_max_n(budget, "potrf", nb=256, grid=(2, 4),
                                  dtype="float32")
    assert nmax > 0
    m = memmodel.MemoryModel("potrf", nmax, 256, (2, 4), "float32")
    assert m.peak_bytes <= budget
    step = 256 * 4  # nb * lcm(2, 4)
    m2 = memmodel.MemoryModel("potrf", nmax + step, 256, (2, 4), "float32")
    assert m2.peak_bytes > budget
    # more devices -> bigger feasible n
    assert memmodel.predict_max_n(budget, "potrf", nb=256, grid=(4, 4)) > nmax


def test_potrf_f64_routes_staged_above_fused_fit(monkeypatch):
    from slate_tpu.linalg import chol

    monkeypatch.delenv(memmodel.HBM_ENV, raising=False)
    budget = memmodel.V5E_HBM_BYTES
    # the ADVICE r5 failure: the fused form's ~7.2 live copies exceed a
    # v5e at n = 32768 (8 GB matrix); the model must route staged
    assert memmodel.potrf_fused_ll_peak(32768) > budget
    assert memmodel.potrf_staged_peak(32768) < budget
    assert memmodel.potrf_f64_form(32768, concrete=True,
                                   ozaki_dispatch=False,
                                   budget=budget) == "staged"
    assert chol._potrf_f64_form(32768, concrete=True,
                                ozaki_dispatch=False) == "staged"
    # ... and traced calls keep the fused form (staged is eager-only)
    assert memmodel.potrf_f64_form(32768, concrete=False,
                                   ozaki_dispatch=False,
                                   budget=budget) == "fused"
    # small problems stay fused; the ozaki cache ceiling reproduces the
    # on-chip-validated 16384 point
    assert memmodel.potrf_f64_form(8192, concrete=True,
                                   ozaki_dispatch=False,
                                   budget=budget) == "fused"
    assert memmodel.potrf_ozaki_cache_max_n(budget) >= 16384
    assert memmodel.potrf_f64_form(16384, concrete=True,
                                   ozaki_dispatch=True,
                                   budget=budget) == "ozaki"
    assert memmodel.potrf_f64_form(24576, concrete=True,
                                   ozaki_dispatch=True,
                                   budget=budget) == "staged"


def test_hbm_budget_env_override(monkeypatch):
    monkeypatch.setenv(memmodel.HBM_ENV, str(123 * 2**20))
    assert memmodel.hbm_budget() == 123 * 2**20


def test_potrf_c128_routes_by_doubled_itemsize():
    budget = memmodel.V5E_HBM_BYTES
    # c128 peaks are twice f64's: a size whose f64 fused form fits must
    # route staged for complex128 (and never take the f64-only ozaki
    # cache even with the dispatch live)
    n = 12288
    assert memmodel.potrf_fused_fits(n, budget, itemsize=8)
    assert not memmodel.potrf_fused_fits(n, budget, itemsize=16)
    assert memmodel.potrf_f64_form(n, True, False, budget,
                                   itemsize=8) == "fused"
    assert memmodel.potrf_f64_form(n, True, False, budget,
                                   itemsize=16) == "staged"
    assert memmodel.potrf_f64_form(8192, True, True, budget,
                                   itemsize=16) == "fused"


def test_mixed_ladder_residency_arithmetic():
    base = memmodel.mixed_ladder_residency(4096, 256, (2, 4), nrhs=1)
    m64 = memmodel.MemoryModel("potrf", 4096, 256, (2, 4), "float64")
    assert base > 2.0 * m64.stack_bytes  # A64 + A32 + L32 + RHS stacks
    assert memmodel.mixed_ladder_residency(8192, 256, (2, 4)) > base
    # wider RHS blocks grow the two RHS-shaped stacks only
    wide = memmodel.mixed_ladder_residency(4096, 256, (2, 4), nrhs=2048)
    assert wide > base
    assert wide - base < 2.0 * m64.stack_bytes


def test_memwatch_artifact_mem_section_is_empty(mem_report):
    # the process-global mem section is machine-dependent and cannot be
    # --ignore'd by the CI glob; memwatch artifacts must not gate on it
    assert mem_report.get("mem") == {}
    assert not any(k.startswith("mem_") and not k.startswith("mem.")
                   for k in report.load_values(mem_report))


def test_alias_bytes_are_direction_neutral():
    new = {"mem.alias_bytes": 2000.0}
    old = {"mem.alias_bytes": 1000.0}
    failures, compared = report.check_regression(new, old, 1.5)
    assert failures == [] and compared == 0


# ---------------------------------------------------------------------------
# Perfetto memory counter track
# ---------------------------------------------------------------------------


def test_memory_counter_events_validate():
    samples = [
        {"t": 10.0, "live_bytes": 1000.0,
         "bytes_in_use": {"dev0": 500.0, "dev1": 500.0},
         "live_per_device": {"dev0": 400.0}},
        {"t": 10.5, "live_bytes": 2000.0, "bytes_in_use": {},
         "live_per_device": {}},
    ]
    evs = perfetto.memory_counter_events(samples, base=10.0)
    assert any(e["name"] == "mem.live_bytes" for e in evs)
    assert any(e["name"].startswith("mem.bytes_in_use[") for e in evs)
    tr = {"traceEvents": evs, "displayTimeUnit": "ms"}
    assert perfetto.validate_chrome_trace(tr) == []


def test_span_trace_carries_memory_counters():
    obs.reset()
    with obs.force_enabled(), memory.force_sampling():
        with obs.driver_span("memtrace_probe"):
            jnp.zeros((4, 4)).block_until_ready()
    tr = perfetto.chrome_trace()
    assert any(e.get("ph") == "C" and e["name"].startswith("mem.")
               for e in tr["traceEvents"])
    assert perfetto.validate_chrome_trace(tr) == []
    obs.reset()


def test_flight_trace_memory_counter_track():
    events = [{"op": "summa", "k": 0, "phase": "bulk", "device": [0, 0],
               "t0_s": 0.0, "t1_s": 0.1, "bytes": 10.0, "flops": 1.0}]
    mem_samples = [{"t_s": 0.05, "live_bytes": 42.0,
                    "bytes_in_use": {}, "live_per_device": {"d0": 42.0}}]
    tr = perfetto.flight_chrome_trace(events, [], grid=(1, 1),
                                      mem_samples=mem_samples)
    assert any(e.get("ph") == "C" and e["name"].startswith("mem.")
               for e in tr["traceEvents"])
    assert perfetto.validate_chrome_trace(tr) == []


# ---------------------------------------------------------------------------
# chase_apply broadcast engine conversion (ISSUE 9 satellite): the former
# tuple-axis masked psum is now a two-hop rooted broadcast — all three
# lowerings bitwise-identical
# ---------------------------------------------------------------------------


def test_chase_apply_dist_impls_bitwise():
    from slate_tpu.linalg.eig import hb2st
    from slate_tpu.parallel.dist_twostage import chase_apply_dist

    n, w = 64, 8
    rng = np.random.default_rng(42)
    g = rng.standard_normal((n, n))
    band = np.tril(np.triu(g + g.T, -w), w)
    d, e, f2, _ = hb2st(jnp.asarray(band), w)
    z = jnp.asarray(rng.standard_normal((n, n)))
    mesh = mesh24()
    ref = np.asarray(chase_apply_dist(f2.vs, f2.taus, z, n, w, mesh,
                                      bcast_impl="psum"))
    for impl in ("ring", "doubling", "auto"):
        got = np.asarray(chase_apply_dist(f2.vs, f2.taus, z, n, w, mesh,
                                          bcast_impl=impl))
        assert np.array_equal(got, ref), impl
