"""QR/LQ/least-squares tests — orthogonality + residual gates mirroring
test/test_geqrf.cc, test_gelqf.cc, test_unmqr.cc, test_gels.cc."""

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.linalg.qr import (
    cholqr_array,
    gelqf_array,
    gelqf_l,
    gels_array,
    gels_cholqr_array,
    gels_qr_array,
    geqrf_array,
    geqrf_q,
    geqrf_r,
    unmlq_array,
    unmqr_array,
)
from slate_tpu.types import Op, Side
from slate_tpu.utils.testing import generate


def _check_qr(a, f, tol=1e-12):
    m, n = a.shape
    q = np.asarray(geqrf_q(f))
    r = np.asarray(geqrf_r(f))
    k = min(m, n)
    assert np.abs(q.conj().T @ q - np.eye(k)).max() < tol * m
    assert np.abs(q @ r - a).max() / max(np.abs(a).max(), 1) < tol * m


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("shape", [(60, 40), (40, 40), (200, 90)])
def test_geqrf(dtype, shape):
    a = generate("rands", *shape, dtype=dtype, seed=1)
    _check_qr(a, geqrf_array(jnp.asarray(a)))


def test_geqrf_large_recursive():
    a = generate("rands", 300, 150, dtype=np.float64, seed=2)
    _check_qr(a, geqrf_array(jnp.asarray(a)))


def test_unmqr_right_side():
    m, n, k = 50, 30, 20
    a = generate("rands", m, n, np.complex128, seed=3)
    c = generate("rands", k, m, np.complex128, seed=4)
    f = geqrf_array(jnp.asarray(a))
    q = np.asarray(geqrf_q(f, full=True))
    out = np.asarray(unmqr_array(Side.Right, Op.NoTrans, f, jnp.asarray(c)))
    np.testing.assert_allclose(out, c @ q, atol=1e-10)
    outh = np.asarray(unmqr_array(Side.Right, Op.ConjTrans, f, jnp.asarray(c)))
    np.testing.assert_allclose(outh, c @ q.conj().T, atol=1e-10)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_gelqf(dtype):
    m, n = 40, 70
    a = generate("rands", m, n, dtype, seed=5)
    f = gelqf_array(jnp.asarray(a))
    l = np.asarray(gelqf_l(f))
    # Q rows orthonormal: reconstruct via applying Q^H to [L 0] padded
    eye = jnp.eye(n, dtype=f.lv.dtype)
    q = np.asarray(unmlq_array(Side.Left, Op.NoTrans, f, eye))[:n]
    lq = np.zeros((m, n), dtype=np.asarray(f.lv).dtype)
    lq[:, :m] = l
    np.testing.assert_allclose(lq @ q, a, atol=1e-10)
    np.testing.assert_allclose(q @ q.conj().T, np.eye(n), atol=1e-10)


def test_cholqr():
    a = generate("rands", 120, 30, np.float64, seed=6)
    q, r = cholqr_array(jnp.asarray(a))
    qn, rn = np.asarray(q), np.asarray(r)
    assert np.abs(qn.T @ qn - np.eye(30)).max() < 1e-9
    np.testing.assert_allclose(qn @ rn, a, atol=1e-10)


def test_gels_overdetermined():
    m, n = 100, 40
    a = generate("rands", m, n, np.float64, seed=7)
    b = generate("rands", m, 3, np.float64, seed=8)
    x = np.asarray(gels_qr_array(jnp.asarray(a), jnp.asarray(b)))
    xref = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(x, xref, atol=1e-9)
    x2 = np.asarray(gels_cholqr_array(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(x2, xref, atol=1e-8)


def test_gels_underdetermined():
    m, n = 30, 80
    a = generate("rands", m, n, np.float64, seed=9)
    b = generate("rands", m, 2, np.float64, seed=10)
    x = np.asarray(gels_array(jnp.asarray(a), jnp.asarray(b)))
    xref = np.linalg.lstsq(a, b, rcond=None)[0]  # minimum-norm solution
    np.testing.assert_allclose(a @ x, b, atol=1e-10)
    np.testing.assert_allclose(x, xref, atol=1e-9)


def test_unmqr_complex_trans_rejected():
    # complex Op.Trans is undefined for compact-WY (LAPACK 'N'/'C' only):
    # must raise, not silently apply Q^H (review-found bug)
    import pytest
    from slate_tpu.types import SlateError
    a = generate("randn", 24, 16, np.complex128, seed=40)
    f = geqrf_array(jnp.asarray(a))
    c = generate("randn", 24, 4, np.complex128, seed=41)
    with pytest.raises(SlateError):
        unmqr_array(Side.Left, Op.Trans, f, jnp.asarray(c))


def test_geqrf_scan():
    # single-program scanned QR (north-star sizes code path)
    from slate_tpu.linalg.qr import geqrf_scan_array, unmqr_scan_array
    from slate_tpu.types import Op

    rng = np.random.default_rng(40)
    for m, n, nb in [(96, 96, 32), (130, 70, 32)]:
        a = rng.standard_normal((m, n))
        f = geqrf_scan_array(jnp.asarray(a), nb=nb)
        r = np.asarray(f.r)
        r_ext = np.zeros((m, n))
        r_ext[: min(m, n)] = r[: min(m, n)]
        qr = np.asarray(unmqr_scan_array(f, jnp.asarray(r_ext), Op.NoTrans))
        assert np.abs(qr - a).max() / np.abs(a).max() < 1e-13
        b = rng.standard_normal((m, 3))
        rt = np.asarray(
            unmqr_scan_array(f, unmqr_scan_array(f, jnp.asarray(b), Op.ConjTrans))
        )
        assert np.abs(rt - b).max() < 1e-12
