"""Facade tests: LAPACK-named API, C API (native lib via ctypes), tracing,
tester harness — reference analogues lapack_api/, c_api/, Trace, testsweeper."""

import ctypes
import os
import shutil
import subprocess

import jax.numpy as jnp
import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_lapack_api_names():
    from slate_tpu import lapack_api as la

    rng = np.random.default_rng(0)
    n = 24
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    xt = rng.standard_normal((n, 2))
    x, f, info = la.slate_dgesv(a, a @ xt)
    assert info == 0
    assert np.abs(np.asarray(x) - xt).max() < 1e-10
    # bare names + float32 variant exist
    l, info = la.dpotrf(a @ a.T + n * np.eye(n))
    assert info == 0
    c = la.sgemm("N", "N", n, n, n, 1.0, a, a, 0.0, np.zeros((n, n)))
    assert np.asarray(c).dtype == np.float32


def test_lapack_api_gecon():
    from slate_tpu import lapack_api as la

    n = 30
    rng = np.random.default_rng(1)
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    rcond = la.dgecon("1", a)
    assert 0 < rcond <= 1


def _libpython_available():
    """True when the shared libpython the native build links against
    (the -lpythonX.Y tokens hardcoded in native/build.sh) is findable by
    the linker.  Some containers ship a different interpreter (or only a
    static one) — there the C-API build cannot succeed and the tests
    skip with a clear reason instead of erroring (pre-existing breakage,
    CHANGES.md PR 3)."""
    import ctypes.util
    import glob
    import re
    import sysconfig

    build = open(os.path.join(_ROOT, "native", "build.sh")).read()
    needed = set(re.findall(r"-l(python[\w.]+)", build)) or {"python3"}
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    for lib in needed:
        if not (ctypes.util.find_library(lib)
                or glob.glob(os.path.join(libdir, f"lib{lib}.so*"))):
            return False
    return True


def _build_native():
    lib = os.path.join(_ROOT, "native", "lib", "libslatetpu_c.so")
    if not os.path.exists(lib):
        if shutil.which("g++") is None:
            pytest.skip("no g++")
        if not _libpython_available():
            pytest.skip(
                "libpython shared library not available in this container "
                "(native C-API build links -lpython; cannot succeed)"
            )
        subprocess.run(["bash", os.path.join(_ROOT, "native", "build.sh")], check=True)
    return lib


def test_c_api_dgesv():
    lib_path = _build_native()
    lib = ctypes.CDLL(lib_path)
    lib.slate_tpu_dgesv.argtypes = [ctypes.c_int64] * 2 + [ctypes.c_void_p] * 3
    n = 16
    rng = np.random.default_rng(2)
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    xt = rng.standard_normal((n, 1))
    b = a @ xt
    x = np.zeros_like(xt)
    info = lib.slate_tpu_dgesv(n, 1, a.ctypes.data, b.ctypes.data, x.ctypes.data)
    assert info == 0
    assert np.abs(x - xt).max() < 1e-10


def test_c_api_dposv_and_gels():
    lib_path = _build_native()
    lib = ctypes.CDLL(lib_path)
    lib.slate_tpu_dposv.argtypes = [ctypes.c_int64] * 2 + [ctypes.c_void_p] * 3
    lib.slate_tpu_dgels.argtypes = [ctypes.c_int64] * 3 + [ctypes.c_void_p] * 3
    n = 20
    rng = np.random.default_rng(3)
    g = rng.standard_normal((n, n))
    a = g @ g.T + n * np.eye(n)
    xt = rng.standard_normal((n, 1))
    b = a @ xt
    x = np.zeros_like(xt)
    assert lib.slate_tpu_dposv(n, 1, a.ctypes.data, b.ctypes.data, x.ctypes.data) == 0
    assert np.abs(x - xt).max() < 1e-9
    m = 30
    aa = rng.standard_normal((m, n))
    bb = rng.standard_normal((m, 1))
    xx = np.zeros((n, 1))
    assert lib.slate_tpu_dgels(m, n, 1, aa.ctypes.data, bb.ctypes.data, xx.ctypes.data) == 0
    assert np.abs(aa.T @ (aa @ xx - bb)).max() < 1e-9


def test_trace_svg():
    import time

    from slate_tpu.utils import trace

    if shutil.which("g++") is None and not os.path.exists(
        os.path.join(_ROOT, "native", "lib", "libslatetpu_trace.so")
    ):
        pytest.skip("no g++")
    trace.Trace.on()
    with trace.block("gemm", lane=0):
        time.sleep(0.002)
    with trace.block("trsm", lane=1):
        time.sleep(0.001)
    out = trace.Trace.finish("/tmp/slate_tpu_trace_test.svg")
    trace.Trace.off()
    assert out is not None
    svg = open(out).read()
    assert svg.startswith("<svg") and "gemm" in svg and "trsm" in svg
    assert trace.timers["gemm"] > 0


def test_tester_cli():
    r = subprocess.run(
        ["python", os.path.join(_ROOT, "tester.py"), "gemm", "--dim", "64", "--type", "s"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_ROOT),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "pass" in r.stdout


def test_simplified_api():
    from slate_tpu import api
    from slate_tpu.types import Side

    rng = np.random.default_rng(4)
    a = jnp.asarray(rng.standard_normal((16, 8)))
    b = jnp.asarray(rng.standard_normal((8, 12)))
    c = api.multiply(1.0, a, b)
    np.testing.assert_allclose(np.asarray(c), np.asarray(a) @ np.asarray(b), atol=1e-12)
    n = 20
    g = rng.standard_normal((n, n))
    spd = jnp.asarray(g @ g.T + n * np.eye(n))
    xt = rng.standard_normal((n, 1))
    x, info = api.chol_solve(spd, jnp.asarray(np.asarray(spd) @ xt))
    assert int(info) == 0 and np.abs(np.asarray(x) - xt).max() < 1e-9
    w = api.eig_vals(jnp.asarray((g + g.T) / 2))
    assert np.abs(np.asarray(w) - np.linalg.eigvalsh((g + g.T) / 2)).max() < 1e-9


def test_simplified_api_precision_opts(rng):
    # round-3: Option.Precision must reach blas3 through every multiply verb
    import jax.numpy as jnp

    from slate_tpu import api
    from slate_tpu.types import Option, Precision, Side

    a = jnp.asarray(rng.standard_normal((32, 24)))
    b = jnp.asarray(rng.standard_normal((24, 16)))
    ref = np.asarray(a) @ np.asarray(b)
    for tier in (Precision.Fast, Precision.High, Precision.Highest, "fast"):
        out = api.multiply(1.0, a, b, opts={Option.Precision: tier})
        # CPU computes exactly regardless of tier; this asserts the opts
        # path is plumbed (a bad tier value would raise)
        assert np.abs(np.asarray(out) - ref).max() < 1e-12
    h = jnp.asarray(rng.standard_normal((24, 24)))
    h = (h + h.T) / 2
    out = api.hermitian_multiply(Side.Left, 1.0, h, b, opts={"precision": "highest"})
    assert np.abs(np.asarray(out) - np.asarray(h) @ np.asarray(b)).max() < 1e-12
    import pytest

    with pytest.raises(ValueError):
        api.multiply(1.0, a, b, opts={Option.Precision: "warp-speed"})
