"""Eigensolver tests: tridiag tier (sterf/steqr/stedc), two-stage chain
(he2hb/hb2st), heev/hegv drivers — mirrors the reference's test_heev.cc /
test_stedc.cc / test_sterf.cc sweeps with 3-eps-style gates vs numpy."""

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.linalg.eig import heev_array, hegv_array, he2hb, hb2st
from slate_tpu.linalg.tridiag import stedc, steqr, sterf
from slate_tpu.utils.testing import generate


def _tridiag(n, seed):
    rng = np.random.default_rng(seed)
    d = rng.standard_normal(n)
    e = rng.standard_normal(max(n - 1, 0))
    T = np.diag(d)
    if n > 1:
        T += np.diag(e, 1) + np.diag(e, -1)
    return d, e, T


# n=300 exercises the values-only D&C branch (sterf routes past
# _STERF_QR_MAX to stedc_vals; the QR-iteration branch covers the rest)
@pytest.mark.parametrize("n", [1, 2, 5, 60, 300])
def test_sterf(n):
    d, e, T = _tridiag(n, 1)
    w = np.asarray(sterf(jnp.asarray(d), jnp.asarray(e)))
    wref = np.linalg.eigvalsh(T)
    assert np.abs(w - wref).max() < 1e-11 * max(1, np.abs(wref).max())


@pytest.mark.parametrize("n", [2, 33, 100])
def test_steqr(n):
    d, e, T = _tridiag(n, 2)
    w, z = steqr(jnp.asarray(d), jnp.asarray(e))
    w, z = np.asarray(w), np.asarray(z)
    assert np.abs(T @ z - z * w).max() < 1e-10
    assert np.abs(z.T @ z - np.eye(n)).max() < 1e-11


@pytest.mark.parametrize("n", [40, 100, 257])
def test_stedc(n):
    d, e, T = _tridiag(n, 3)
    w, z = stedc(jnp.asarray(d), jnp.asarray(e))
    w, z = np.asarray(w), np.asarray(z)
    wref = np.linalg.eigvalsh(T)
    assert np.abs(w - wref).max() < 1e-12 * max(1, np.abs(wref).max())
    assert np.abs(T @ z - z * w).max() < 1e-12 * max(1, np.abs(wref).max()) * n
    assert np.abs(z.T @ z - np.eye(n)).max() < 1e-13 * n


def test_stedc_deflation_heavy():
    # glued identical blocks: exercises both z-based and close-pole deflation
    d = np.concatenate([np.ones(32), 2 * np.ones(33)])
    e = np.zeros(64)
    e[31] = 0.5
    T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    w, z = stedc(jnp.asarray(d), jnp.asarray(e))
    w, z = np.asarray(w), np.asarray(z)
    assert np.abs(T @ z - z * w).max() < 1e-12
    assert np.abs(z.T @ z - np.eye(65)).max() < 1e-12


def test_he2hb_band_structure():
    n, nb = 80, 16
    a = np.asarray(generate("rands", n, n, np.float64, seed=4))
    a = (a + a.T) / 2
    f = he2hb(jnp.asarray(a), nb)
    band = np.asarray(f.band)
    assert np.abs(np.tril(band, -(nb + 1))).max() == 0
    werr = np.abs(np.linalg.eigvalsh(band) - np.linalg.eigvalsh(a)).max()
    assert werr < 1e-12 * n


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_heev(dtype):
    n = 64
    a = np.asarray(generate("randn", n, n, dtype, seed=5))
    a = (a + a.conj().T) / 2
    w, z = heev_array(jnp.asarray(a), nb=16)
    w, z = np.asarray(w), np.asarray(z)
    wref = np.linalg.eigvalsh(a)
    assert np.abs(w - wref).max() < 1e-12 * max(1, np.abs(wref).max()) * n
    assert np.abs(a @ z - z * w).max() < 1e-12 * n
    assert np.abs(z.conj().T @ z - np.eye(n)).max() < 1e-12 * n


def test_heev_values_only():
    n = 50
    a = np.asarray(generate("rands", n, n, np.float64, seed=6))
    a = (a + a.T) / 2
    w = np.asarray(heev_array(jnp.asarray(a), want_vectors=False, nb=16))
    assert np.abs(w - np.linalg.eigvalsh(a)).max() < 1e-11


def test_hegv():
    n = 40
    rng = np.random.default_rng(7)
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    g = rng.standard_normal((n, n))
    b = g @ g.T + n * np.eye(n)
    w, x, info = hegv_array(jnp.asarray(a), jnp.asarray(b))
    w, x = np.asarray(w), np.asarray(x)
    assert int(info) == 0
    # A x = lambda B x residual + B-orthonormality
    assert np.abs(a @ x - (b @ x) * w).max() / np.abs(a).max() < 1e-10
    assert np.abs(x.T @ b @ x - np.eye(n)).max() < 1e-10


def test_hegv_itype2():
    # itype=2: A B x = lambda x; back-transform is x = L^-H y (hegv.cc:100-105)
    n = 36
    rng = np.random.default_rng(11)
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    g = rng.standard_normal((n, n))
    b = g @ g.T + n * np.eye(n)
    w, x, info = hegv_array(jnp.asarray(a), jnp.asarray(b), itype=2)
    w, x = np.asarray(w), np.asarray(x)
    assert int(info) == 0
    denom = np.abs(a).max() * np.abs(b).max()
    assert np.abs(a @ (b @ x) - x * w).max() / denom < 1e-10
    # itype=2 eigvecs are B-orthonormal: x = L^-H y with y orthonormal
    assert np.abs(x.T @ b @ x - np.eye(n)).max() < 1e-9


def test_hegv_itype3():
    # itype=3: B A x = lambda x; back-transform is x = L y
    n = 36
    rng = np.random.default_rng(12)
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    g = rng.standard_normal((n, n))
    b = g @ g.T + n * np.eye(n)
    w, x, info = hegv_array(jnp.asarray(a), jnp.asarray(b), itype=3)
    w, x = np.asarray(w), np.asarray(x)
    assert int(info) == 0
    denom = np.abs(a).max() * np.abs(b).max()
    assert np.abs(b @ (a @ x) - x * w).max() / denom < 1e-10
    # itype=3 eigvecs are B^-1-orthonormal: x = L y with y orthonormal
    assert np.abs(x.T @ np.linalg.solve(b, x) - np.eye(n)).max() < 1e-9


def test_hesv_indefinite():
    from slate_tpu.linalg.indefinite import hesv_array

    n = 48
    rng = np.random.default_rng(9)
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2  # indefinite with high probability
    xt = rng.standard_normal((n, 2))
    b = a @ xt
    x, f, info = hesv_array(jnp.asarray(a), jnp.asarray(b), nb=16)
    assert int(info) == 0
    assert np.abs(np.asarray(x) - xt).max() / np.abs(xt).max() < 1e-10


def test_gtsv_pivoting():
    from slate_tpu.linalg.indefinite import gtsv_array

    # zero diagonal forces the adjacent-row swap path
    n = 10
    dl = np.ones(n - 1)
    d = np.zeros(n)
    du = 2 * np.ones(n - 1)
    T = np.diag(d) + np.diag(dl, -1) + np.diag(du, 1)
    b = np.arange(n, dtype=np.float64)
    x, info = gtsv_array(jnp.asarray(dl), jnp.asarray(d), jnp.asarray(du), jnp.asarray(b))
    assert int(info) == 0
    assert np.abs(T @ np.asarray(x) - b).max() < 1e-12


def test_heev_staged_matches_fused():
    from slate_tpu.linalg.eig import heev_staged

    n = 70
    a = np.asarray(generate("randn", n, n, np.float64, seed=13))
    a = (a + a.T) / 2
    w, z = heev_staged(jnp.asarray(a), nb=16)
    w, z = np.asarray(w), np.asarray(z)
    wref = np.linalg.eigvalsh(a)
    assert np.abs(w - wref).max() < 1e-12 * max(1, np.abs(wref).max()) * n
    assert np.abs(a @ z - z * w).max() < 1e-12 * n
    assert np.abs(z.T @ z - np.eye(n)).max() < 1e-12 * n
    wv = np.asarray(heev_staged(jnp.asarray(a), want_vectors=False, nb=16))
    assert np.abs(np.sort(wv) - wref).max() < 1e-11 * n


def test_chase_apply_staged_matches_fused():
    # the sweep-block staged apply (heev_staged/svd_staged's chip path at
    # n >= _APPLY_SEG_SWEEPS; the fused apply outruns the TPU worker
    # watchdog at 16384) must be numerically identical to the fused form
    import slate_tpu.linalg.eig as eig
    from slate_tpu.linalg.eig import (
        _chase_apply_staged, _chase_sweep_apply, hb2st,
    )

    rng = np.random.default_rng(11)
    n, w = 96, 8
    g = rng.standard_normal((n, n))
    band = np.tril(np.triu(g + g.T, -w), w)
    d, e, f2, _ = hb2st(jnp.asarray(band), w)
    z = jnp.asarray(rng.standard_normal((n, n)))
    saved = (eig._APPLY_SEG_SWEEPS, eig._APPLY_REF_AREA, eig._APPLY_MIN_BLOCK)
    # shrink all three knobs so the area scaling yields genuinely
    # multi-block dispatch at this tiny size (the sweep floor would
    # otherwise collapse it to the single-program fast path)
    eig._APPLY_SEG_SWEEPS, eig._APPLY_REF_AREA, eig._APPLY_MIN_BLOCK = 16, n * n, 8
    try:
        for adjoint in (False, True):
            ref = np.asarray(_chase_sweep_apply(f2.vs, f2.taus, z, n, w, adjoint))
            got = np.asarray(_chase_apply_staged(f2.vs, f2.taus, z, n, w, adjoint))
            assert np.abs(ref - got).max() < 1e-12, adjoint
    finally:
        eig._APPLY_SEG_SWEEPS, eig._APPLY_REF_AREA, eig._APPLY_MIN_BLOCK = saved
