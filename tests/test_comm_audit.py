"""comm_audit coverage (ISSUE 1 satellite): nested audit_scope
multiplicities, the jit-cache-hit-records-nothing contract, and the
trace-time recording the jaxpr lint's loop-audit check relies on.

ISSUE 5 (broadcast engine): the analytic SUMMA/ABFT volume formulas gain
a per-impl factor — the masked-psum lowering records per-device payload
bytes, the ppermute ring/doubling lowerings record per-hop LINK bytes
summing to (s-1) payloads per broadcast — and the acceptance assertion:
the ring lowering's loop-broadcast wire bytes are <= 0.55x the
masked-psum path's for summa, potrf, and LU-nopiv at identical
schedules (they are exactly 0.5x under the documented byte model)."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from slate_tpu.parallel.comm import audit_scope, comm_audit, psum_a


def _wire_bytes(records, p, q, prefix):
    """Per-device wire bytes of the ``prefix``-op records under the
    documented byte model: psum (ring all-reduce) 2B(s-1)/s; ppermute
    hop records carry link bytes, B_hop/s per device."""
    total = 0.0
    for op, nbytes, mult in records:
        if not op.startswith(prefix):
            continue
        s = p if "[p]" in op else q
        if prefix == "psum":
            total += 2 * nbytes * (s - 1) / s * mult
        else:
            total += nbytes / s * mult
    return total


def _psum_i(x):
    return psum_a(x, "i")


def test_audit_records_payload_and_op():
    with comm_audit() as recs:
        jax.make_jaxpr(jax.vmap(_psum_i, axis_name="i"))(jnp.zeros((4, 8)))
    assert len(recs) == 1
    op, nbytes, mult = recs[0]
    assert op == "psum[i]"
    assert nbytes == 8 * jnp.zeros((), jnp.float64).dtype.itemsize
    assert mult == 1


def test_nested_audit_scope_multiplies():
    def fn(x):
        with audit_scope(2):
            a = _psum_i(x)
            with audit_scope(3):
                b = _psum_i(x)
        c = _psum_i(x)
        return a + b + c

    with comm_audit() as recs:
        jax.make_jaxpr(jax.vmap(fn, axis_name="i"))(jnp.zeros((4, 8)))
    mults = [m for _, _, m in recs]
    assert mults == [2, 6, 1]


def test_audit_scope_restored_on_exit():
    from slate_tpu.parallel.comm import _AUDIT_MULT

    with audit_scope(5):
        assert _AUDIT_MULT[-1] == 5
    assert _AUDIT_MULT[-1] == 1


def test_jit_cache_hit_records_nothing():
    jitted = jax.jit(jax.vmap(_psum_i, axis_name="i"))
    x = jnp.ones((4, 8))
    jitted(x).block_until_ready()  # compile outside any audit
    with comm_audit() as recs:
        jitted(x).block_until_ready()  # cache hit: no re-trace
    assert recs == []
    # a fresh trace (cleared caches) records again
    jax.clear_caches()
    with comm_audit() as recs2:
        jax.jit(jax.vmap(_psum_i, axis_name="i"))(x).block_until_ready()
    assert len(recs2) == 1


def test_audit_nesting_restores_outer_audit():
    with comm_audit() as outer:
        jax.make_jaxpr(jax.vmap(_psum_i, axis_name="i"))(jnp.zeros((2, 2)))
        with comm_audit() as inner:
            jax.make_jaxpr(jax.vmap(_psum_i, axis_name="i"))(jnp.zeros((2, 4)))
        jax.make_jaxpr(jax.vmap(_psum_i, axis_name="i"))(jnp.zeros((2, 8)))
    assert len(inner) == 1
    assert len(outer) == 2  # inner context's record does not leak out


def test_lint_flags_unscoped_loop_collective():
    """Regression: a toy kernel with a loop collective and NO audit_scope
    must be reported by the slate_lint loop-audit check."""
    from slate_tpu.analysis.jaxpr_checks import check_loop_audit

    def bad(x):
        return jax.lax.fori_loop(0, 3, lambda i, a: a + _psum_i(a), x)

    with comm_audit() as recs:
        closed = jax.make_jaxpr(jax.vmap(bad, axis_name="i"))(jnp.zeros((2, 4)))
    found = check_loop_audit(closed, list(recs), "driver:toy")
    assert len(found) == 1 and found[0].rule == "loop-audit"

    def good(x):
        with audit_scope(3):
            return jax.lax.fori_loop(0, 3, lambda i, a: a + _psum_i(a), x)

    with comm_audit() as recs2:
        closed2 = jax.make_jaxpr(jax.vmap(good, axis_name="i"))(jnp.zeros((2, 4)))
    assert check_loop_audit(closed2, list(recs2), "driver:toy") == []


def test_summarize_ring_estimates():
    """tools/comm_audit.summarize: ring-lowering receive estimates."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "comm_audit_tool",
        os.path.join(os.path.dirname(__file__), "..", "tools", "comm_audit.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    p, q = 2, 4
    recs = [("psum[p]", 100, 2), ("all_gather[q]", 50, 1), ("psum_scatter[q]", 80, 3)]
    payload, recv, calls, by_op = mod.summarize(recs, p, q)
    assert payload == 100 * 2 + 50 + 80 * 3
    assert calls == 6
    expect = 2 * 100 * (p - 1) / p * 2 + 50 * (q - 1) + 80 * (q - 1) / q * 3
    assert np.isclose(recv, expect)
    assert set(by_op) == {"psum", "all_gather", "psum_scatter"}

    # ppermute hop records carry link bytes: recv estimate is nbytes / s,
    # so a whole rooted q-axis broadcast of B=120 (3 single-pair ring
    # hops) receives 120 * (q-1)/q per device — half psum's 2B(q-1)/q
    hop_recs = [("ppermute[q]", 120, 1)] * (q - 1)
    _, recv_ring, _, by_op2 = mod.summarize(hop_recs, p, q)
    assert np.isclose(recv_ring, 120 * (q - 1) / q)
    _, recv_psum, _, _ = mod.summarize([("psum[q]", 120, 1)], p, q)
    assert np.isclose(recv_ring, recv_psum / 2)
    assert set(by_op2) == {"ppermute"}


@pytest.mark.parametrize("impl", ["psum", "ring", "doubling"])
def test_summa_payload_matches_analytic_bcast_volume(impl):
    """ISSUE 2 satellite + ISSUE 5 per-impl factor: prove the comm_audit
    counters against the closed-form SUMMA communication volume.

    C-stationary SUMMA broadcasts, per k-step and per device, its A
    tile-column (mtl tiles) along mesh axis 'q' and its B tile-row (ntl
    tiles) along 'p'.  Under ``psum`` each broadcast is one masked psum
    whose audited per-device payload sums to kt * (mtl + ntl) * nb^2 *
    itemsize EXACTLY at every lookahead depth.  Under the ppermute
    engine the same schedule records per-hop LINK bytes: every rooted
    broadcast of payload B moves exactly (s-1) * B across the axis'
    links (ring: s-1 single-pair hops; doubling: log2 s hops of 1, 2,
    4... pairs), so the total is kt * ((q-1)*mtl + (p-1)*ntl) * nb^2 *
    itemsize — and the per-device wire bytes are exactly HALF the psum
    path's 2B(s-1)/s.  Lookahead still only moves records between the
    prologue (multiplicity 1) and the scoped loop, never the totals."""
    import jax.numpy as jnp

    from slate_tpu.parallel import from_dense, gemm_summa, make_mesh
    from slate_tpu.types import MethodGemm

    p, q, n, nb = 2, 4, 64, 8
    mesh = make_mesh(p, q, devices=jax.devices("cpu")[:8])
    rng = np.random.default_rng(0)
    a = from_dense(jnp.asarray(rng.standard_normal((n, n)), jnp.float32),
                   mesh, nb)
    b = from_dense(jnp.asarray(rng.standard_normal((n, n)), jnp.float32),
                   mesh, nb)
    kt, mtl, ntl = a.nt, a.mt // p, b.nt // q
    itemsize = 4  # f32
    a_bytes, b_bytes = mtl * nb * nb * itemsize, ntl * nb * nb * itemsize
    if impl == "psum":
        expect_total = kt * (a_bytes + b_bytes)
        ops = {"psum[p]", "psum[q]"}
        # one record per broadcast
        recs_per_bcast = {"psum[q]": 1, "psum[p]": 1}
    else:
        # (s-1) link-payloads per rooted broadcast, either hop schedule
        expect_total = kt * ((q - 1) * a_bytes + (p - 1) * b_bytes)
        ops = {"ppermute[p]", "ppermute[q]"}
        hops = (lambda s: s - 1) if impl == "ring" else (
            lambda s: max(1, s.bit_length() - 1))
        recs_per_bcast = {"ppermute[q]": hops(q), "ppermute[p]": hops(p)}

    for la in (0, 1, 2):
        jax.clear_caches()  # counters record at trace time only
        with comm_audit() as recs:
            gemm_summa(1.0, a, b, method=MethodGemm.GemmC, lookahead=la,
                       bcast_impl=impl).tiles.block_until_ready()

        assert sum(nbytes * m for _, nbytes, m in recs) == expect_total, la

        # per-op totals: multiplicity-weighted link bytes per op
        by_op_bytes, by_op_recs = {}, {}
        for op, nbytes, m in recs:
            by_op_bytes[op] = by_op_bytes.get(op, 0) + nbytes * m
            by_op_recs[op] = by_op_recs.get(op, 0) + m
        assert set(by_op_bytes) == ops
        # A column panel rides axis 'q' (bcast_from_col), B row panel 'p'
        if impl == "psum":
            assert by_op_bytes["psum[q]"] == kt * a_bytes
            assert by_op_bytes["psum[p]"] == kt * b_bytes
        else:
            assert by_op_bytes["ppermute[q]"] == kt * (q - 1) * a_bytes
            assert by_op_bytes["ppermute[p]"] == kt * (p - 1) * b_bytes
        # strict: all records scoped at kt; depth d: d prologue record
        # sets at multiplicity 1 + the loop records at kt - d
        n_per_step = sum(recs_per_bcast.values())
        mults = sorted(m for _, _, m in recs)
        if la == 0:
            assert mults == [kt] * n_per_step
        else:
            assert mults == [1] * (la * n_per_step) + [kt - la] * n_per_step

        # the acceptance ratio: engine wire bytes are exactly half psum's
        if impl != "psum":
            wire = _wire_bytes(recs, p, q, "ppermute")
            psum_wire = kt * (2 * a_bytes * (q - 1) / q
                             + 2 * b_bytes * (p - 1) / p)
            assert np.isclose(wire, psum_wire / 2)


@pytest.mark.parametrize("impl", ["psum", "ring"])
def test_ft_summa_checksum_broadcast_volume(impl):
    """ISSUE 4 satellite + ISSUE 5 per-impl factor: the ABFT overhead is
    proven, not estimated.

    The checksum-carrying SUMMA broadcasts the same two panels per
    k-step as the plain kernel — the checksum tiles are just more tiles
    of the augmented grid riding the same broadcasts.  Under ``psum``
    the audited per-device payload equals kt * (mtl_aug + ntl_aug) *
    nb^2 * itemsize EXACTLY; under ``ring`` the link-byte total is the
    same panels x (s-1) hop payloads.  The delta against the plain
    kernel's analytic volume is exactly the augmentation — no hidden
    collectives, no extra steps — under either lowering."""
    import math

    import jax.numpy as jnp

    from slate_tpu.ft import abft
    from slate_tpu.ft.policy import FtPolicy
    from slate_tpu.parallel import make_mesh

    p, q, n, nb = 2, 4, 64, 8
    mesh = make_mesh(p, q, devices=jax.devices("cpu")[:8])
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
    mt = nt = kt = n // nb  # already a multiple of lcm(p, q)
    lcm = math.lcm(p, q)
    aug = ((mt + 2 + lcm - 1) // lcm) * lcm  # +2 checksum tile rows, re-padded
    mtl_aug, ntl_aug = aug // p, aug // q
    itemsize = 4  # f32
    a_bytes = mtl_aug * nb * nb * itemsize  # A panel (axis 'q') per step
    b_bytes = ntl_aug * nb * nb * itemsize  # B panel (axis 'p') per step

    jax.clear_caches()  # counters record at trace time only
    with comm_audit() as recs:
        c, rep = abft.gemm_ft(1.0, a, b, mesh, nb, policy=FtPolicy.Detect,
                              bcast_impl=impl)
    assert rep.clean
    np.testing.assert_allclose(
        np.asarray(c), np.asarray(a) @ np.asarray(b), rtol=1e-5, atol=1e-4
    )

    total = sum(nbytes * m for _, nbytes, m in recs)
    by_op = {}
    for op, nbytes, m in recs:
        by_op[op] = by_op.get(op, 0) + nbytes * m

    if impl == "psum":
        expect_total = kt * (a_bytes + b_bytes)
        assert set(by_op) == {"psum[p]", "psum[q]"}
        assert by_op["psum[q]"] == kt * a_bytes
        assert by_op["psum[p]"] == kt * b_bytes
        # overhead vs the plain kernel's analytic volume: exactly the
        # augmented tile rows/cols (2 checksum + lcm pad), nothing else
        mtl, ntl = mt // p, nt // q
        plain_total = kt * (mtl + ntl) * nb * nb * itemsize
        assert total - plain_total == (
            kt * ((mtl_aug - mtl) + (ntl_aug - ntl)) * nb * nb * itemsize
        )
    else:
        expect_total = kt * ((q - 1) * a_bytes + (p - 1) * b_bytes)
        assert set(by_op) == {"ppermute[p]", "ppermute[q]"}
        assert by_op["ppermute[q]"] == kt * (q - 1) * a_bytes
        assert by_op["ppermute[p]"] == kt * (p - 1) * b_bytes
        # same per-impl halving as the plain kernel: ring wire bytes are
        # exactly half the masked-psum wire bytes for the same schedule
        wire = _wire_bytes(recs, p, q, "ppermute")
        psum_wire = kt * (2 * a_bytes * (q - 1) / q
                          + 2 * b_bytes * (p - 1) / p)
        assert np.isclose(wire, psum_wire / 2)
    assert total == expect_total


# ---------------------------------------------------------------------------
# ISSUE 5 acceptance: the ring lowering moves <= 0.55x the masked-psum
# loop-broadcast bytes for summa, potrf, and LU-nopiv at identical
# schedules (exactly 0.5x under the documented byte model).
# ---------------------------------------------------------------------------


def _loop_bcast_wire(fn, impl):
    """Per-device broadcast wire bytes of one driver run under ``impl``.
    Every psum in these three kernels IS a broadcast (the pivot/panel
    gathers are all_gather records and excluded by construction), so the
    broadcast subset is the psum records under psum and the ppermute
    records under ring/doubling."""
    from slate_tpu.parallel.comm import use_bcast_impl

    jax.clear_caches()  # counters record at trace time only
    with comm_audit() as recs:
        with use_bcast_impl(impl):
            fn()
    prefix = "psum" if impl == "psum" else "ppermute"
    assert any(op.startswith(prefix) for op, _, _ in recs), (impl, recs)
    return _wire_bytes(list(recs), 2, 4, prefix)


@pytest.mark.parametrize("op", ["summa", "potrf", "lu_nopiv"])
def test_ring_halves_loop_broadcast_bytes(op, rng):
    from slate_tpu.parallel import from_dense, gemm_summa, make_mesh
    from slate_tpu.parallel.dist_chol import potrf_dist
    from slate_tpu.parallel.dist_lu import getrf_nopiv_dist
    from slate_tpu.types import MethodGemm

    p, q, n, nb = 2, 4, 64, 8
    mesh = make_mesh(p, q, devices=jax.devices("cpu")[:8])
    a = jnp.asarray(rng.standard_normal((n, n)))
    if op == "summa":
        ad = from_dense(a, mesh, nb)
        bd = from_dense(jnp.asarray(rng.standard_normal((n, n))), mesh, nb)
        fn = lambda: gemm_summa(
            1.0, ad, bd, method=MethodGemm.GemmC
        ).tiles.block_until_ready()
    elif op == "potrf":
        spd = a @ a.T + n * jnp.eye(n)
        sd = from_dense(spd, mesh, nb, diag_pad_one=True)
        fn = lambda: potrf_dist(sd)[0].tiles.block_until_ready()
    else:
        tl = jnp.asarray(np.tril(np.asarray(a)) + n * np.eye(n))
        td = from_dense(tl, mesh, nb, diag_pad_one=True)
        fn = lambda: getrf_nopiv_dist(td)[0].tiles.block_until_ready()

    psum_wire = _loop_bcast_wire(fn, "psum")
    ring_wire = _loop_bcast_wire(fn, "ring")
    dbl_wire = _loop_bcast_wire(fn, "doubling")
    # the acceptance bound, and the exact model value behind it
    assert ring_wire <= 0.55 * psum_wire, (op, ring_wire, psum_wire)
    assert np.isclose(ring_wire, psum_wire / 2), (op, ring_wire, psum_wire)
    # doubling moves the same total link bytes as ring (s-1 payloads)
    assert np.isclose(dbl_wire, ring_wire), (op, dbl_wire, ring_wire)


def test_bcast_diag_tile_two_hop_volume():
    """ISSUE 5 satellite: bcast_diag_tile was a masked DOUBLE psum (two
    all-reduces of one tile, ~4x ring-broadcast bytes); under the engine
    it is a two-hop rooted broadcast — (p-1) row-axis hops then (q-1)
    column-axis hops of exactly one tile each — delivering the owner's
    exact bytes to every device."""
    from jax.sharding import PartitionSpec as P

    from slate_tpu.parallel import make_mesh
    from slate_tpu.parallel.comm import (
        bcast_diag_tile, bcast_impl_scope, shard_map_compat,
    )
    from slate_tpu.parallel.mesh import COL_AXIS, ROW_AXIS

    p, q, nb = 2, 4, 4
    mesh = make_mesh(p, q, devices=jax.devices("cpu")[:8])
    spec = P(ROW_AXIS, COL_AXIS)
    rng_ = np.random.default_rng(3)
    # (mt, nt, nb, nb) cyclic tile stack with distinguishable tiles
    mt = nt = 4
    tiles = jnp.asarray(rng_.standard_normal((mt, nt, nb, nb)), jnp.float32)

    outs, recs_by = {}, {}
    for impl in ("psum", "ring", "doubling"):
        def kernel(t_loc):
            # deliver tile (k, k) for k = 3 (owner (1, 3) on the 2x4 grid)
            return bcast_diag_tile(t_loc, 3, p, q, nb)[None, None]

        jax.clear_caches()
        with comm_audit() as recs:
            with bcast_impl_scope(impl):
                out = shard_map_compat(
                    kernel, mesh=mesh, in_specs=(spec,), out_specs=spec,
                    check_vma=False,
                )(tiles)
            out = np.asarray(jax.block_until_ready(out))
        outs[impl], recs_by[impl] = out, list(recs)

    # every device got tile (3, 3), bitwise, under every lowering
    for impl, out in outs.items():
        for i in range(p):
            for j in range(q):
                np.testing.assert_array_equal(
                    out[i, j], np.asarray(tiles[3, 3]), err_msg=impl
                )

    tile_bytes = nb * nb * 4
    # legacy: two full all-reduces of one tile
    assert recs_by["psum"] == [("psum[p]", tile_bytes, 1),
                               ("psum[q]", tile_bytes, 1)]
    # engine: (p-1) + (q-1) single-tile link payloads, row hop first
    for impl in ("ring", "doubling"):
        total = sum(nbytes * m for _, nbytes, m in recs_by[impl])
        assert total == ((p - 1) + (q - 1)) * tile_bytes, impl
        wire = _wire_bytes(recs_by[impl], p, q, "ppermute")
        psum_wire = _wire_bytes(recs_by["psum"], p, q, "psum")
        assert wire == pytest.approx(psum_wire / 2)


def test_ppermute_a_records_link_bytes():
    """The audited ppermute wrapper records operand bytes x pairs (link
    bytes for the hop), under the enclosing audit_scope multiplicity."""
    from slate_tpu.parallel.comm import ppermute_a

    def fn(x):
        with audit_scope(5):
            return ppermute_a(x, "i", [(0, 1), (1, 0)])

    with comm_audit() as recs:
        jax.make_jaxpr(jax.vmap(fn, axis_name="i"))(jnp.zeros((2, 4)))
    assert recs == [("ppermute[i]", 2 * 4 * 8, 5)]  # 2 pairs x 4 f64 lanes


def test_redistribute_shardmap_wire_volume():
    """ISSUE 12: the shardmap redistribution's audited ppermute link
    bytes equal the analytic ``redistribute_wire_bytes`` formula — the
    ring schedule moves each device's source block through p*(q-1)
    column rotations (q link pairs each) and p-1 row rotations (p
    pairs), at one source-block payload per hop."""
    from conftest import cpu_devices
    from slate_tpu.parallel import dist, from_dense
    from slate_tpu.parallel.mesh import make_mesh

    p, q = 2, 4
    mesh = make_mesh(p, q, devices=cpu_devices(8))
    mesh2 = make_mesh(4, 2, devices=cpu_devices(8))
    d = from_dense(jnp.zeros((96, 96)), mesh, 8)
    cmap = dist._shardmap_coord_map(mesh, mesh2)
    mt2 = dist.padded_tiles(d.m, d.nb, mesh2)
    nt2 = dist.padded_tiles(d.n, d.nb, mesh2)
    dims = (4, 2, d.tiles.shape[0], d.tiles.shape[1], mt2, nt2, d.nb)
    with comm_audit() as recs:
        jax.make_jaxpr(lambda t: dist._redist_shardmap_fn(
            t, mesh, p, q, dims, cmap, False))(d.tiles)
    got = sum(nb_ * m for op, nb_, m in recs if op.startswith("ppermute"))
    want = dist.redistribute_wire_bytes(
        d.tiles.shape, p, q, d.tiles.dtype.itemsize)
    # per-device block = (12/2)*(12/4) = 18 tiles of 8x8 f64 = 9216 B;
    # hops: 2*(4-1) col rotations x 4 pairs + 1 row rotation x 2 pairs
    assert want == 9216 * (2 * 3 * 4 + 1 * 2)
    assert got == want
    # memory contract: the exchange holds ONE circulating source block +
    # ONE destination block per device — 1/(p q)-class residency, not
    # the eager path's full replicated grid
    n_pp = sum(1 for op, _, _ in recs if op.startswith("ppermute"))
    assert n_pp == p * q - 1


@pytest.mark.parametrize("impl", ["psum", "ring"])
def test_ft_her2k_checksum_broadcast_volume(impl):
    """ISSUE 13 satellite: her2k_ft's checksum traffic is proven, not
    estimated.  The checksum-carrying her2k runs dist_blas3's schedule
    verbatim (the shared ``_her2k_panels`` fetch: per step, per operand,
    one rooted column-panel broadcast along 'q' + one transposed
    all_gather along 'p') — the checksum tiles are just more tiles of
    the row-augmented operands, so the audited delta against the plain
    kernel is EXACTLY the augmentation rows (2 checksum + lcm pad) on
    both collectives, for both operands, under either lowering.  Traces
    only (make_jaxpr): audit records are a trace-time surface, so no
    kernels execute and no jit caches are cleared."""
    import math

    import jax.numpy as jnp

    from slate_tpu.ft import abft, inject
    from slate_tpu.parallel import make_mesh
    from slate_tpu.parallel.dist import from_dense
    from slate_tpu.parallel.dist_blas3 import _her2k_jit
    from slate_tpu.types import Uplo

    p, q, n, nb = 2, 4, 64, 8
    mesh = make_mesh(p, q, devices=jax.devices("cpu")[:8])
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((n, n)))
    b = jnp.asarray(rng.standard_normal((n, n)))
    mt = kt = n // nb
    lcm = math.lcm(p, q)
    aug = ((mt + 2 + lcm - 1) // lcm) * lcm  # +2 checksum rows, re-padded
    mtl, mtl_aug = mt // p, aug // p
    itemsize = 8  # f64
    pan = nb * nb * itemsize  # one tile of a column panel

    def totals(recs):
        by_op = {}
        for op, nbytes, m in recs:
            by_op[op] = by_op.get(op, 0) + nbytes * m
        return by_op

    ad, bd = from_dense(a, mesh, nb), from_dense(b, mesh, nb)
    with comm_audit() as plain_recs:
        jax.make_jaxpr(lambda x, y: _her2k_jit(
            x, y, None, 1.0, 0.0, mesh, p, q, kt, n, Uplo.Lower, True,
            True, 0, impl))(ad.tiles, bd.tiles)
    a_aug, b_aug, _c, mt_, kt_ = abft._encode_her2k(a, b, None, nb, mesh)
    assert (mt_, kt_) == (mt, kt)
    fi, fv = inject.spec_arrays("her2k")
    adx, bdx = from_dense(a_aug, mesh, nb), from_dense(b_aug, mesh, nb)
    with comm_audit() as ft_recs:
        jax.make_jaxpr(lambda x, y, i, v: abft._ft_her2k_jit(
            x, y, None, 1.0, 0.0, mesh, p, q, kt, n, True, 0, impl,
            i, v))(adx.tiles, bdx.tiles, jnp.asarray(fi), jnp.asarray(fv))

    plain, ft = totals(plain_recs), totals(ft_recs)
    # the transposed gather along 'p' is impl-independent payload bytes
    delta_rows = mtl_aug - mtl
    assert ft["all_gather[p]"] - plain["all_gather[p]"] == \
        kt * 2 * delta_rows * pan
    if impl == "psum":
        assert set(ft) == {"psum[q]", "all_gather[p]"}
        assert ft["psum[q]"] == kt * 2 * mtl_aug * pan
        assert ft["psum[q]"] - plain["psum[q]"] == kt * 2 * delta_rows * pan
    else:
        assert set(ft) == {"ppermute[q]", "all_gather[p]"}
        assert ft["ppermute[q]"] == kt * 2 * (q - 1) * mtl_aug * pan
        assert ft["ppermute[q]"] - plain["ppermute[q]"] == \
            kt * 2 * (q - 1) * delta_rows * pan
