"""comm_audit coverage (ISSUE 1 satellite): nested audit_scope
multiplicities, the jit-cache-hit-records-nothing contract, and the
trace-time recording the jaxpr lint's loop-audit check relies on."""

import jax
import jax.numpy as jnp
import numpy as np

from slate_tpu.parallel.comm import audit_scope, comm_audit, psum_a


def _psum_i(x):
    return psum_a(x, "i")


def test_audit_records_payload_and_op():
    with comm_audit() as recs:
        jax.make_jaxpr(jax.vmap(_psum_i, axis_name="i"))(jnp.zeros((4, 8)))
    assert len(recs) == 1
    op, nbytes, mult = recs[0]
    assert op == "psum[i]"
    assert nbytes == 8 * jnp.zeros((), jnp.float64).dtype.itemsize
    assert mult == 1


def test_nested_audit_scope_multiplies():
    def fn(x):
        with audit_scope(2):
            a = _psum_i(x)
            with audit_scope(3):
                b = _psum_i(x)
        c = _psum_i(x)
        return a + b + c

    with comm_audit() as recs:
        jax.make_jaxpr(jax.vmap(fn, axis_name="i"))(jnp.zeros((4, 8)))
    mults = [m for _, _, m in recs]
    assert mults == [2, 6, 1]


def test_audit_scope_restored_on_exit():
    from slate_tpu.parallel.comm import _AUDIT_MULT

    with audit_scope(5):
        assert _AUDIT_MULT[-1] == 5
    assert _AUDIT_MULT[-1] == 1


def test_jit_cache_hit_records_nothing():
    jitted = jax.jit(jax.vmap(_psum_i, axis_name="i"))
    x = jnp.ones((4, 8))
    jitted(x).block_until_ready()  # compile outside any audit
    with comm_audit() as recs:
        jitted(x).block_until_ready()  # cache hit: no re-trace
    assert recs == []
    # a fresh trace (cleared caches) records again
    jax.clear_caches()
    with comm_audit() as recs2:
        jax.jit(jax.vmap(_psum_i, axis_name="i"))(x).block_until_ready()
    assert len(recs2) == 1


def test_audit_nesting_restores_outer_audit():
    with comm_audit() as outer:
        jax.make_jaxpr(jax.vmap(_psum_i, axis_name="i"))(jnp.zeros((2, 2)))
        with comm_audit() as inner:
            jax.make_jaxpr(jax.vmap(_psum_i, axis_name="i"))(jnp.zeros((2, 4)))
        jax.make_jaxpr(jax.vmap(_psum_i, axis_name="i"))(jnp.zeros((2, 8)))
    assert len(inner) == 1
    assert len(outer) == 2  # inner context's record does not leak out


def test_lint_flags_unscoped_loop_collective():
    """Regression: a toy kernel with a loop collective and NO audit_scope
    must be reported by the slate_lint loop-audit check."""
    from slate_tpu.analysis.jaxpr_checks import check_loop_audit

    def bad(x):
        return jax.lax.fori_loop(0, 3, lambda i, a: a + _psum_i(a), x)

    with comm_audit() as recs:
        closed = jax.make_jaxpr(jax.vmap(bad, axis_name="i"))(jnp.zeros((2, 4)))
    found = check_loop_audit(closed, list(recs), "driver:toy")
    assert len(found) == 1 and found[0].rule == "loop-audit"

    def good(x):
        with audit_scope(3):
            return jax.lax.fori_loop(0, 3, lambda i, a: a + _psum_i(a), x)

    with comm_audit() as recs2:
        closed2 = jax.make_jaxpr(jax.vmap(good, axis_name="i"))(jnp.zeros((2, 4)))
    assert check_loop_audit(closed2, list(recs2), "driver:toy") == []


def test_summarize_ring_estimates():
    """tools/comm_audit.summarize: ring-lowering receive estimates."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "comm_audit_tool",
        os.path.join(os.path.dirname(__file__), "..", "tools", "comm_audit.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    p, q = 2, 4
    recs = [("psum[p]", 100, 2), ("all_gather[q]", 50, 1), ("psum_scatter[q]", 80, 3)]
    payload, recv, calls, by_op = mod.summarize(recs, p, q)
    assert payload == 100 * 2 + 50 + 80 * 3
    assert calls == 6
    expect = 2 * 100 * (p - 1) / p * 2 + 50 * (q - 1) + 80 * (q - 1) / q * 3
    assert np.isclose(recv, expect)
    assert set(by_op) == {"psum", "all_gather", "psum_scatter"}


def test_summa_payload_matches_analytic_bcast_volume():
    """ISSUE 2 satellite: prove the comm_audit counters against the
    closed-form SUMMA communication volume, not just exercise them.

    C-stationary SUMMA broadcasts, per k-step and per device, its A
    tile-column (mtl tiles) along mesh axis 'q' and its B tile-row (ntl
    tiles) along 'p' — each as one masked psum of nb x nb tiles.  The
    audited per-device payload must equal kt * (mtl + ntl) * nb^2 *
    itemsize EXACTLY at every lookahead depth; the depth only moves
    broadcasts between the prologue (multiplicity 1) and the
    audit-scoped loop (multiplicity kt - depth), never changing the
    per-op totals (ISSUE 3: lookahead changes when bytes move, not how
    many)."""
    import jax.numpy as jnp

    from slate_tpu.parallel import from_dense, gemm_summa, make_mesh
    from slate_tpu.types import MethodGemm

    p, q, n, nb = 2, 4, 64, 8
    mesh = make_mesh(p, q, devices=jax.devices("cpu")[:8])
    rng = np.random.default_rng(0)
    a = from_dense(jnp.asarray(rng.standard_normal((n, n)), jnp.float32),
                   mesh, nb)
    b = from_dense(jnp.asarray(rng.standard_normal((n, n)), jnp.float32),
                   mesh, nb)
    kt, mtl, ntl = a.nt, a.mt // p, b.nt // q
    itemsize = 4  # f32
    expect_total = kt * (mtl + ntl) * nb * nb * itemsize

    for la in (0, 1, 2):
        jax.clear_caches()  # counters record at trace time only
        with comm_audit() as recs:
            gemm_summa(1.0, a, b, method=MethodGemm.GemmC,
                       lookahead=la).tiles.block_until_ready()

        assert sum(nbytes * m for _, nbytes, m in recs) == expect_total, la

        # per-op totals: multiplicity-weighted step counts sum to kt
        steps = {}
        payload = {}
        for op, nbytes, m in recs:
            steps[op] = steps.get(op, 0) + m
            payload.setdefault(op, nbytes)
            assert payload[op] == nbytes  # same panel size in every record
        assert set(steps) == {"psum[p]", "psum[q]"}
        # A column panel rides axis 'q' (bcast_from_col), B row panel 'p'
        assert steps["psum[q]"] == kt and payload["psum[q]"] == mtl * nb * nb * itemsize
        assert steps["psum[p]"] == kt and payload["psum[p]"] == ntl * nb * nb * itemsize
        # strict: one scoped record per op; depth d: d prologue records
        # at multiplicity 1 per op + one loop record at kt - d
        mults = sorted(m for _, _, m in recs)
        if la == 0:
            assert mults == [kt, kt]
        else:
            assert mults == [1] * (2 * la) + [kt - la] * 2


def test_ft_summa_checksum_broadcast_volume():
    """ISSUE 4 satellite: the ABFT overhead is proven, not estimated.

    The checksum-carrying SUMMA broadcasts the same two panels per
    k-step as the plain kernel — the checksum tiles are just more tiles
    of the augmented grid riding the same masked psums, so the audited
    per-device payload must equal kt * (mtl_aug + ntl_aug) * nb^2 *
    itemsize EXACTLY, where the augmented local tile counts come from
    appending 2 checksum tile rows/cols and re-padding to the mesh lcm.
    The delta against the plain kernel's analytic volume is therefore
    exactly the augmentation — no hidden collectives, no extra steps."""
    import math

    import jax.numpy as jnp

    from slate_tpu.ft import abft
    from slate_tpu.ft.policy import FtPolicy
    from slate_tpu.parallel import make_mesh

    p, q, n, nb = 2, 4, 64, 8
    mesh = make_mesh(p, q, devices=jax.devices("cpu")[:8])
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
    mt = nt = kt = n // nb  # already a multiple of lcm(p, q)
    lcm = math.lcm(p, q)
    aug = ((mt + 2 + lcm - 1) // lcm) * lcm  # +2 checksum tile rows, re-padded
    mtl_aug, ntl_aug = aug // p, aug // q
    itemsize = 4  # f32

    jax.clear_caches()  # counters record at trace time only
    with comm_audit() as recs:
        c, rep = abft.gemm_ft(1.0, a, b, mesh, nb, policy=FtPolicy.Detect)
    assert rep.clean
    np.testing.assert_allclose(
        np.asarray(c), np.asarray(a) @ np.asarray(b), rtol=1e-5, atol=1e-4
    )

    total = sum(nbytes * m for _, nbytes, m in recs)
    expect_total = kt * (mtl_aug + ntl_aug) * nb * nb * itemsize
    assert total == expect_total

    # overhead vs the plain kernel's analytic volume: exactly the
    # augmented tile rows/cols (2 checksum + lcm pad), nothing else
    mtl, ntl = mt // p, nt // q
    plain_total = kt * (mtl + ntl) * nb * nb * itemsize
    assert total - plain_total == (
        kt * ((mtl_aug - mtl) + (ntl_aug - ntl)) * nb * nb * itemsize
    )

    # per-op split: A panel rides axis 'q', B panel axis 'p', kt steps
    # each, constant payload — same schedule shape as the plain kernel
    steps, payload = {}, {}
    for op, nbytes, m in recs:
        steps[op] = steps.get(op, 0) + m
        payload.setdefault(op, nbytes)
        assert payload[op] == nbytes
    assert set(steps) == {"psum[p]", "psum[q]"}
    assert steps["psum[q]"] == kt
    assert payload["psum[q]"] == mtl_aug * nb * nb * itemsize
    assert steps["psum[p]"] == kt
    assert payload["psum[p]"] == ntl_aug * nb * nb * itemsize
