"""Norm drivers + condition estimators (test_norm.cc / gecondest etc.)."""

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.core.matrix import HermitianMatrix, TriangularMatrix
from slate_tpu.linalg.chol import potrf_array
from slate_tpu.linalg.lu import getrf_array
from slate_tpu.linalg.norms import col_norms, gecondest, norm, pocondest, trcondest
from slate_tpu.types import Norm, NormScope, Uplo
from slate_tpu.utils.testing import generate


def _a(n=30, seed=1):
    return np.asarray(generate("rands", n, n, np.float64, seed=seed))


@pytest.mark.parametrize(
    "nt,ref",
    [
        (Norm.One, lambda a: np.abs(a).sum(0).max()),
        (Norm.Inf, lambda a: np.abs(a).sum(1).max()),
        (Norm.Max, lambda a: np.abs(a).max()),
        (Norm.Fro, lambda a: np.linalg.norm(a)),
    ],
)
def test_genorm(nt, ref):
    a = _a()
    got = float(norm(nt, jnp.asarray(a)))
    np.testing.assert_allclose(got, ref(a), rtol=1e-13)


def test_henorm_uses_triangle():
    a = _a()
    h = HermitianMatrix.from_array(jnp.asarray(a), Uplo.Lower)
    full = np.tril(a) + np.tril(a, -1).T
    np.testing.assert_allclose(float(norm(Norm.One, h)), np.abs(full).sum(0).max(), rtol=1e-13)


def test_col_norms():
    a = _a()
    np.testing.assert_allclose(np.asarray(col_norms(jnp.asarray(a))), np.abs(a).max(0))


def test_gecondest():
    n = 40
    a = _a(n, seed=2) + n * np.eye(n)
    f = getrf_array(jnp.asarray(a))
    anorm = np.abs(a).sum(0).max()
    rcond = float(gecondest(Norm.One, f, anorm))
    true_rcond = 1.0 / (anorm * np.abs(np.linalg.inv(a)).sum(0).max())
    # estimator guarantees a lower bound within a modest factor
    assert 0.1 * true_rcond <= rcond <= 10 * true_rcond


def test_pocondest():
    n = 40
    g = _a(n, seed=3)
    a = g @ g.T + n * np.eye(n)
    l, info = potrf_array(jnp.asarray(a))
    assert int(info) == 0
    anorm = np.abs(a).sum(0).max()
    rcond = float(pocondest(Norm.One, TriangularMatrix.from_array(l, Uplo.Lower), anorm))
    true_rcond = 1.0 / (anorm * np.abs(np.linalg.inv(a)).sum(0).max())
    assert 0.05 * true_rcond <= rcond <= 20 * true_rcond


def test_trcondest():
    n = 30
    t = np.tril(_a(n, seed=4)) + n * np.eye(n)
    rcond = float(trcondest(Norm.One, TriangularMatrix.from_array(jnp.asarray(t), Uplo.Lower)))
    anorm = np.abs(t).sum(0).max()
    true_rcond = 1.0 / (anorm * np.abs(np.linalg.inv(t)).sum(0).max())
    assert 0.05 * true_rcond <= rcond <= 20 * true_rcond
