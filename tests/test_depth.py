"""Round-3 depth tests (VERDICT r2 weak item 7): staged eig/svd drivers in
CI, bf16 mesh runs, scan-vs-recursive LU pivot equivalence on adversarial
ties, condest on near-singular fixtures."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import cpu_devices


@pytest.fixture
def rng():
    return np.random.default_rng(11)


@pytest.mark.slow  # tier-1 budget relief (ISSUE 11): consistency
# check, not a per-kernel identity gate; ci/run_ci.sh's full pytest
# pass still runs it
def test_heev_staged_matches_fused(rng):
    # staged drivers (one XLA program per phase) must agree with the fused
    # heev_array bit-for-bit in structure (same kernels, same order)
    from slate_tpu.linalg.eig import heev_array, heev_staged

    n = 100
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    aj = jnp.asarray(a)
    w1, z1 = heev_array(aj, nb=32)
    w2, z2 = heev_staged(aj, nb=32)
    assert np.abs(np.asarray(w1) - np.asarray(w2)).max() < 1e-12
    resid = np.abs(a @ np.asarray(z2) - np.asarray(z2) * np.asarray(w2)).max()
    assert resid < 1e-11 * max(1, np.abs(np.asarray(w2)).max())


@pytest.mark.slow  # tier-1 budget relief (ISSUE 11): consistency
# check, not a per-kernel identity gate; ci/run_ci.sh's full pytest
# pass still runs it
def test_svd_staged_matches_fused(rng):
    from slate_tpu.linalg.svd import svd_array, svd_staged

    a = rng.standard_normal((96, 80))
    aj = jnp.asarray(a)
    u1, s1, vh1 = svd_array(aj, nb=32)
    u2, s2, vh2 = svd_staged(aj, nb=32)
    assert np.abs(np.asarray(s1) - np.asarray(s2)).max() < 1e-12
    rec = (np.asarray(u2) * np.asarray(s2)) @ np.asarray(vh2)
    assert np.abs(rec - a).max() < 1e-11 * np.asarray(s2)[0]


def test_getrf_scan_vs_recursive_pivot_ties(rng):
    # adversarial ties: equal-magnitude candidates in one panel column must
    # resolve identically in the scanned and recursive formulations
    from slate_tpu.linalg.lu import getrf_array, getrf_scan_array

    n = 64
    a = rng.standard_normal((n, n))
    a[:, 0] = 0.0
    a[[3, 17, 33], 0] = 2.0       # three-way exact tie in column 0
    a[5, 1] = a[9, 1] = -4.0      # tie below the diagonal in column 1
    f1 = getrf_array(jnp.asarray(a))
    f2 = getrf_scan_array(jnp.asarray(a))
    p1, p2 = np.asarray(f1.perm), np.asarray(f2.perm)
    np.testing.assert_array_equal(p1, p2)
    np.testing.assert_allclose(np.asarray(f1.lu), np.asarray(f2.lu), atol=1e-12)


def test_condest_near_singular(rng):
    # condition estimates on a near-singular fixture must explode ~1/delta
    # and stay finite/ordered on the well-conditioned one
    import scipy.linalg  # noqa: F401
    from slate_tpu.linalg import getrf_array
    from slate_tpu.linalg.norms import gecondest
    from slate_tpu.ops.tile_ops import genorm
    from slate_tpu.types import Norm

    n = 48
    q, _ = np.linalg.qr(rng.standard_normal((n, n)))
    for delta, lo, hi in [(1e-10, 1e8, 1e14), (1.0, 1.0, 1e4)]:
        svals = np.linspace(1.0, 2.0, n)
        svals[-1] = delta
        a = (q * svals) @ q.T
        aj = jnp.asarray(a)
        f = getrf_array(aj)
        anorm = genorm(Norm.One, aj)
        rcond = float(gecondest(Norm.One, f, anorm))
        est_cond = 1.0 / max(rcond, 1e-300)
        assert lo <= est_cond <= hi, (delta, est_cond)


def test_condest_exactly_singular(rng):
    from slate_tpu.linalg import getrf_array
    from slate_tpu.linalg.norms import gecondest
    from slate_tpu.ops.tile_ops import genorm
    from slate_tpu.types import Norm

    n = 32
    a = rng.standard_normal((n, n))
    a[:, 7] = a[:, 3]  # exactly rank-deficient
    aj = jnp.asarray(a)
    f = getrf_array(aj)
    rcond = float(gecondest(Norm.One, f, genorm(Norm.One, aj)))
    assert rcond < 1e-12  # estimator must report (near-)singularity


def test_bf16_mesh_gemm(rng):
    # CPU-mesh suite never ran bf16 before: SUMMA with bf16 tiles
    from slate_tpu.parallel import gemm_mesh, make_mesh

    mesh = make_mesh(2, 4, devices=cpu_devices(8))
    n = 64
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    c = gemm_mesh(1.0, jnp.asarray(a, jnp.bfloat16), jnp.asarray(b, jnp.bfloat16), mesh, nb=16)
    ref = a @ b
    rel = np.abs(np.asarray(c, np.float32) - ref).max() / np.abs(ref).max()
    assert rel < 0.05  # bf16 inputs: ~2^-8 relative


def test_bf16_mesh_potrf(rng):
    from slate_tpu.parallel import make_mesh, potrf_mesh, to_dense

    mesh = make_mesh(2, 2, devices=cpu_devices(4))
    n = 32
    g = rng.standard_normal((n, n)).astype(np.float32)
    a = g @ g.T + n * np.eye(n, dtype=np.float32)
    l, info = potrf_mesh(jnp.asarray(a, jnp.bfloat16), mesh, nb=8)
    assert int(info) == 0
    ld = np.tril(np.asarray(to_dense(l), np.float32))
    rel = np.abs(ld @ ld.T - a).max() / np.abs(a).max()
    assert rel < 0.1


def test_segmented_chase_matches_fused(rng):
    # round-3: the per-range segmented wavefront dispatch (the n > 8192
    # escape hatch) must be bit-identical to the fused chase
    from slate_tpu.linalg.eig import hb2st
    from slate_tpu.linalg.svd import tb2bd

    n, w = 120, 16
    g = rng.standard_normal((n, n))
    band = np.tril(np.triu(g + g.T, -w), w)
    d1, e1, f1, _ = hb2st(jnp.asarray(band), w)
    d2, e2, f2, _ = hb2st(jnp.asarray(band), w, segments=3)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(f1.vs), np.asarray(f2.vs))
    ub = np.triu(np.tril(rng.standard_normal((n, n)), w), 0)
    o1 = tb2bd(jnp.asarray(ub), w)
    o2 = tb2bd(jnp.asarray(ub), w, segments=4)
    np.testing.assert_array_equal(np.asarray(o1[0]), np.asarray(o2[0]))
    np.testing.assert_array_equal(np.asarray(o1[2].rvs), np.asarray(o2[2].rvs))


@pytest.mark.slow  # tier-1 budget relief (ISSUE 11): consistency
# check, not a per-kernel identity gate; ci/run_ci.sh's full pytest
# pass still runs it
def test_chunked_values_merge_matches_monolithic(rng, monkeypatch):
    # the wide-merge values branch (2s >= _CHUNK_AT) must agree with the
    # monolithic path it replaces — forced down to test scale
    import slate_tpu.linalg.tridiag as tg

    n = 300
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    w_ref = np.asarray(tg.stedc_vals(jnp.asarray(d), jnp.asarray(e)))
    monkeypatch.setattr(tg, "_CHUNK_AT", 128)
    monkeypatch.setattr(tg, "_CHUNK_COLS", 32)
    w_chunk = np.asarray(tg.stedc_vals(jnp.asarray(d), jnp.asarray(e)))
    T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    wl = np.linalg.eigvalsh(T)
    assert np.abs(w_chunk - wl).max() < 1e-11
    assert np.abs(w_chunk - w_ref).max() < 1e-11
