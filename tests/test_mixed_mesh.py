"""Mixed-precision mesh solve tests (ISSUE 8).

The acceptance surface of the f32-factor + f64-refine rebuild
(parallel/dist_refine.py):

- Option.MixedPrecision=off is jaxpr-IDENTICAL to the direct f64
  gesv_mesh/posv_mesh path; auto (the default) factors in f32 and meets
  the refine.py residual gate ||r|| <= ||x|| * ||A|| * eps * sqrt(n).
- The fused refinement loop performs ZERO host syncs per iteration
  (transfer-guard dispatch of the warm program).
- Ill-conditioned escalation: IR fails -> GMRES-IR -> full-f64 fallback,
  with the ir.* counters recording the ladder.
- opts threading: the mixed solve is bitwise-invariant under
  Lookahead x BcastImpl (every component kernel is), and the Pallas
  panel lowering still meets the residual gate.
- The Ozaki residual SUMMA is bitwise-stable across mesh shapes and its
  comm-audit wire bytes are exactly slice_count/8 x the plain f64 SUMMA
  volume (per BcastImpl factor), proven analytically.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from slate_tpu.parallel import make_mesh
from slate_tpu.parallel.comm import comm_audit
from slate_tpu.parallel.dist import from_dense, to_dense
from slate_tpu.parallel.dist_refine import (
    residual_comm_bytes,
    resolve_mixed,
    use_mixed,
)
from slate_tpu.parallel.drivers import (
    _gesv_mesh_plain,
    _posv_mesh_plain,
    gesv_mesh,
    gesv_mixed_gmres_mesh,
    gesv_mixed_mesh,
    posv_mesh,
    posv_mixed_mesh,
)
from slate_tpu.types import Option

from conftest import cpu_devices

N, NB, NRHS = 96, 16, 2


def mesh24():
    return make_mesh(2, 4, devices=cpu_devices(8))


def _well(rng):
    a = rng.standard_normal((N, N)) + N * np.eye(N)
    return jnp.asarray(a)


def _spd(rng):
    g = rng.standard_normal((N, N))
    return jnp.asarray(g @ g.T / N + 2 * np.eye(N))


def _cond(rng, c):
    q1, _ = np.linalg.qr(rng.standard_normal((N, N)))
    q2, _ = np.linalg.qr(rng.standard_normal((N, N)))
    s = np.logspace(0, -np.log10(c), N)
    return jnp.asarray(q1 @ np.diag(s) @ q2)


def _rhs(rng, k=NRHS):
    return jnp.asarray(rng.standard_normal((N, k)))


def _gate(a, x, b):
    """The refine.py residual gate: ||r||inf <= ||x||inf ||A||inf eps sqrt(n)."""
    a, x, b = map(np.asarray, (a, x, b))
    r = b - a @ x
    rnorm = np.abs(r).sum(axis=1).max()
    xnorm = np.abs(x).sum(axis=1).max()
    anorm = np.abs(a).sum(axis=1).max()
    return rnorm <= xnorm * anorm * np.finfo(np.float64).eps * np.sqrt(N)


# ---------------------------------------------------------------------------
# off-switch: trace identity with the direct path; auto: default-on
# ---------------------------------------------------------------------------


def test_resolve_chain_defaults_to_auto():
    assert resolve_mixed(None) == "auto"
    assert resolve_mixed({Option.MixedPrecision: "off"}) == "off"
    with use_mixed("ir"):
        assert resolve_mixed(None) == "ir"
        assert resolve_mixed({Option.MixedPrecision: "gmres"}) == "gmres"
    with pytest.raises(ValueError):
        resolve_mixed({Option.MixedPrecision: "sometimes"})


@pytest.mark.parametrize("kind", ["gesv", "posv"])
def test_off_is_jaxpr_identical_to_direct_path(kind, rng):
    mesh = mesh24()
    a = _well(rng) if kind == "gesv" else _spd(rng)
    b = _rhs(rng)
    off = {Option.MixedPrecision: "off"}
    drv = gesv_mesh if kind == "gesv" else posv_mesh
    plain = _gesv_mesh_plain if kind == "gesv" else _posv_mesh_plain
    j_off = jax.make_jaxpr(lambda x, y: drv(x, y, mesh, NB, opts=off))(a, b)
    j_plain = jax.make_jaxpr(lambda x, y: plain(x, y, mesh, NB, opts=off))(a, b)
    assert str(j_off) == str(j_plain)


def test_traced_f64_driver_keeps_direct_path(rng):
    # the ladder is host-driven (per-tier convergence readbacks between
    # programs): under an outer trace there is no host, so a traced f64
    # call must keep the direct path — same jaxpr as before the routing
    # existed, and jit over the public driver must still work
    mesh = mesh24()
    a = _spd(rng)
    b = _rhs(rng)
    j_auto = jax.make_jaxpr(lambda x, y: posv_mesh(x, y, mesh, NB))(a, b)
    j_plain = jax.make_jaxpr(lambda x, y: _posv_mesh_plain(x, y, mesh, NB))(a, b)
    assert str(j_auto) == str(j_plain)
    x, info = jax.jit(lambda x, y: posv_mesh(x, y, mesh, NB))(a, b)
    assert int(info) == 0
    assert _gate(a, x, b)


def test_non_f64_never_routes(rng):
    # f32 input: no mixed tier exists below it — direct path, identical
    mesh = mesh24()
    a = _spd(rng).astype(jnp.float32)
    b = _rhs(rng).astype(jnp.float32)
    j_auto = jax.make_jaxpr(lambda x, y: posv_mesh(x, y, mesh, NB))(a, b)
    j_plain = jax.make_jaxpr(lambda x, y: _posv_mesh_plain(x, y, mesh, NB))(a, b)
    assert str(j_auto) == str(j_plain)


@pytest.mark.parametrize("kind", ["gesv", "posv"])
def test_auto_routes_through_f32_factor_and_meets_gate(kind, rng):
    from slate_tpu.obs import REGISTRY

    mesh = mesh24()
    a = _well(rng) if kind == "gesv" else _spd(rng)
    b = _rhs(rng)
    drv = gesv_mesh if kind == "gesv" else posv_mesh
    before = REGISTRY.counter_value("ir.solves", op=kind)
    x, info = drv(a, b, mesh, NB)  # default = auto: the mixed ladder
    assert int(info) == 0
    assert _gate(a, x, b)
    # the ladder ran (the ir.* surface is how a service observes it)
    assert REGISTRY.counter_value("ir.solves", op=kind) == before + 1
    # and the refinement really did the work from an f32 factor: the
    # mixed driver agrees with the routed result bitwise (same programs)
    mixed_drv = gesv_mixed_mesh if kind == "gesv" else posv_mixed_mesh
    x2, iters, info2 = mixed_drv(a, b, mesh, NB)
    assert int(info2) == 0 and int(iters) >= 0
    np.testing.assert_array_equal(np.asarray(x), np.asarray(x2))


# ---------------------------------------------------------------------------
# accuracy: well/ill-conditioned, multi-RHS, at the residual gate
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cond,max_iters", [(1e2, 4), (1e8, 30)])
def test_mixed_accuracy_at_gate(cond, max_iters, rng):
    mesh = mesh24()
    a = _cond(rng, cond)
    b = _rhs(rng, 3)  # multi-RHS
    x, iters, info = gesv_mixed_mesh(a, b, mesh, NB)
    assert int(info) == 0
    assert 0 <= int(iters) <= max_iters
    assert _gate(a, x, b)
    # mixed-vs-f64: the direct f64 solve also satisfies the same gate —
    # the mixed path's accuracy contract is the f64 path's
    xf, info_f = _gesv_mesh_plain(a, b, mesh, NB)
    assert _gate(a, xf, b)


def test_posv_lower_only_storage_routes_correctly(rng):
    # the potrf contract reads only the lower triangle, so lower-only
    # storage is a valid posv input; the routed refinement must mirror
    # it before computing residuals (or it would "converge" on the wrong
    # nonsymmetric operator with info == 0)
    mesh = mesh24()
    full = _spd(rng)
    low = jnp.tril(full)
    b = _rhs(rng)
    x, info = posv_mesh(low, b, mesh, NB)  # default = auto
    assert int(info) == 0
    assert _gate(full, x, b)  # the gate is vs the SYMMETRIC operator
    # and lower-only input is bitwise the full-storage routing
    xf, _ = posv_mesh(full, b, mesh, NB)
    np.testing.assert_array_equal(np.asarray(x), np.asarray(xf))


def test_posv_mixed_failed_factor_is_nan(rng):
    mesh = mesh24()
    b = _rhs(rng)
    x, iters, info = posv_mixed_mesh(jnp.asarray(-np.eye(N)), b, mesh, NB)
    assert int(info) != 0
    assert int(iters) == -1
    assert np.all(np.isnan(np.asarray(x)))


# ---------------------------------------------------------------------------
# escalation: IR -> GMRES -> full-f64 fallback
# ---------------------------------------------------------------------------


def test_escalation_ladder_ill_conditioned(rng):
    from slate_tpu.obs import REGISTRY

    mesh = mesh24()
    a = _cond(rng, 1e12)  # far beyond the f32 factor's reach
    b = _rhs(rng)
    # tier 1 alone: IR reports non-convergence honestly
    _x, iters, info = gesv_mixed_mesh(a, b, mesh, NB)
    assert int(info) == 0 and int(iters) == -1
    # the routed default walks the whole ladder and still returns an
    # f64-grade answer (the fallback tier IS the direct f64 solve)
    esc0 = REGISTRY.counter_value("ir.escalated_gmres", op="gesv")
    fb0 = REGISTRY.counter_value("ir.fallback", op="gesv")
    x, info = gesv_mesh(a, b, mesh, NB)
    assert int(info) == 0
    assert _gate(a, x, b)
    assert REGISTRY.counter_value("ir.escalated_gmres", op="gesv") == esc0 + 1
    assert REGISTRY.counter_value("ir.fallback", op="gesv") == fb0 + 1
    # the fallback answer is bitwise the direct path's
    xf, _ = _gesv_mesh_plain(a, b, mesh, NB)
    np.testing.assert_array_equal(np.asarray(x), np.asarray(xf))


def test_gmres_tier_converges_where_ir_does(rng):
    mesh = mesh24()
    a = _well(rng)
    b = _rhs(rng)
    x, rnorm, info = gesv_mixed_gmres_mesh(a, b, mesh, NB)
    assert int(info) == 0
    # the GMRES tier's own contract is the LEFT-PRECONDITIONED tolerance
    # ||M^-1(b - A x)|| <= eps sqrt(n) ||b|| (gesv_mixed_gmres.cc / the
    # refine.py convention) — the measured rnorm must meet it...
    eps = np.finfo(np.float64).eps
    tol = eps * np.sqrt(N) * np.linalg.norm(np.asarray(b), axis=0).max()
    assert float(rnorm) <= tol
    # ...and the unpreconditioned backward error stays f64-grade
    r = np.asarray(b) - np.asarray(a) @ np.asarray(x)
    denom = np.abs(np.asarray(a)).sum(axis=1).max() * max(
        np.abs(np.asarray(x)).max(), 1e-300)
    assert np.abs(r).max() / denom < 1e-11
    # pinning mode=gmres runs GMRES as tier 1 — that is a REQUESTED
    # tier, not an escalation, so the escalation counter must not move
    from slate_tpu.obs import REGISTRY

    esc0 = REGISTRY.counter_value("ir.escalated_gmres", op="gesv")
    xg, info = gesv_mesh(a, b[:, :1], mesh, NB,
                         opts={Option.MixedPrecision: "gmres"})
    assert int(info) == 0
    assert REGISTRY.counter_value("ir.escalated_gmres", op="gesv") == esc0


# ---------------------------------------------------------------------------
# opts threading: lookahead x bcast-impl bitwise invariance; pallas panels
# ---------------------------------------------------------------------------


def test_mixed_opts_threading_bitwise_invariant(rng):
    mesh = mesh24()
    a = _spd(rng)
    b = _rhs(rng)
    outs = []
    for la in (0, 2):
        for bi in ("psum", "ring"):
            x, iters, info = posv_mixed_mesh(
                a, b, mesh, NB,
                opts={Option.Lookahead: la, Option.BcastImpl: bi},
            )
            assert int(info) == 0 and int(iters) >= 0
            outs.append(np.asarray(x))
    for o in outs[1:]:
        np.testing.assert_array_equal(outs[0], o)


def test_mixed_pallas_panels_meet_gate(rng):
    # Option.PanelImpl=pallas reroutes the f32 factor's panel phases to
    # the fused kernels (interpret mode on CPU) — different bits
    # (documented explicit-inverse class), same accuracy contract
    mesh = mesh24()
    a = _spd(rng)
    b = _rhs(rng)
    x, iters, info = posv_mixed_mesh(
        a, b, mesh, NB, opts={Option.PanelImpl: "pallas"}
    )
    assert int(info) == 0 and int(iters) >= 0
    assert _gate(a, x, b)


# ---------------------------------------------------------------------------
# Ozaki residual: bitwise across mesh shapes; comm bytes proven
# ---------------------------------------------------------------------------


def test_ozaki_residual_bitwise_across_mesh_shapes(rng):
    from slate_tpu.parallel.summa import gemm_summa_ozaki

    a = np.asarray(_well(rng))
    x = rng.standard_normal((N, NRHS))
    b = rng.standard_normal((N, NRHS))
    outs = {}
    for p, q in [(2, 4), (1, 8), (2, 2)]:
        mesh = make_mesh(p, q, devices=cpu_devices(p * q))
        ad = from_dense(jnp.asarray(a), mesh, NB, diag_pad_one=True)
        xd = from_dense(jnp.asarray(x), mesh, NB)
        bd = from_dense(jnp.asarray(b), mesh, NB)
        outs[(p, q)] = np.asarray(
            to_dense(gemm_summa_ozaki(-1.0, ad, xd, 1.0, bd))
        )
    ref = b - a @ x
    for grid, out in outs.items():
        # f64-grade accurate...
        assert np.abs(out - ref).max() < 1e-11, grid
        # ...and BITWISE identical to every other mesh shape
        np.testing.assert_array_equal(outs[(2, 4)], out, err_msg=str(grid))


def test_ozaki_mixed_solve_meets_gate(rng):
    mesh = mesh24()
    a = _well(rng)
    b = _rhs(rng)
    x, iters, info = gesv_mixed_mesh(
        a, b, mesh, NB, opts={Option.ResidualImpl: "ozaki"}
    )
    assert int(info) == 0 and int(iters) >= 0
    assert _gate(a, x, b)


@pytest.mark.parametrize("impl", ["psum", "ring"])
def test_ozaki_residual_comm_volume_analytic(impl, rng):
    """The acceptance criterion: the Ozaki residual SUMMA moves exactly
    slice_count(=9)/8 x the plain f64 SUMMA wire bytes — the digit planes
    are int8 on the identical broadcast schedule."""
    from slate_tpu.parallel.summa import gemm_summa, gemm_summa_ozaki
    from slate_tpu.types import MethodGemm

    p, q = 2, 4
    mesh = make_mesh(p, q, devices=cpu_devices(8))
    ad = from_dense(_well(rng), mesh, NB, diag_pad_one=True)
    xd = from_dense(_rhs(rng), mesh, NB)
    bd = from_dense(_rhs(rng), mesh, NB)
    mt, ntb, kt = ad.tiles.shape[0], bd.tiles.shape[1], ad.nt

    def total(records):
        return sum(nbytes * m for _, nbytes, m in records)

    jax.clear_caches()  # audit records at trace time only
    with comm_audit() as recs_oz:
        gemm_summa_ozaki(-1.0, ad, xd, 1.0, bd,
                         bcast_impl=impl).tiles.block_until_ready()
    jax.clear_caches()
    with comm_audit() as recs_f64:
        gemm_summa(-1.0, ad, xd, 1.0, bd, method=MethodGemm.GemmC,
                   bcast_impl=impl).tiles.block_until_ready()

    expect_oz = residual_comm_bytes(mt, ntb, kt, NB, p, q, impl, "ozaki")
    expect_f64 = residual_comm_bytes(mt, ntb, kt, NB, p, q, impl, "f64")
    assert total(recs_oz) == expect_oz
    assert total(recs_f64) == expect_f64
    assert total(recs_oz) * 8 == total(recs_f64) * 9  # 9 int8 planes vs f64


def test_refine_loop_audited_volume(rng):
    """The fused refinement program's trace-time audit carries the
    residual SUMMA at the loop multiplicity: under the masked-psum
    lowering the int8 digit-plane records are exactly the analytic
    per-iteration volume x (max_iter + 1) — the worst-case audit the
    lint loop-audit contract requires for a dynamic-trip while_loop
    (plus the norm-pair reductions riding the same scope)."""
    mesh = mesh24()
    a = _well(rng)
    b = _rhs(rng)
    max_iter = 5
    jax.clear_caches()
    with comm_audit() as recs:
        gesv_mixed_mesh(
            a, b, mesh, NB, max_iter=max_iter,
            opts={Option.ResidualImpl: "ozaki", Option.BcastImpl: "psum"},
        )
    p, q = 2, 4
    ad = from_dense(a, mesh, NB, diag_pad_one=True)
    bd = from_dense(b, mesh, NB)
    mt, ntb, kt = ad.tiles.shape[0], bd.tiles.shape[1], ad.nt
    mtl, ntl = mt // p, ntb // q
    # the int8 plane payloads are unique byte sizes in the whole program
    a_pan, x_pan = 9 * mtl * NB * NB, 9 * ntl * NB * NB
    got = sum(nbytes * m for op, nbytes, m in recs
              if op.startswith("psum") and nbytes in (a_pan, x_pan))
    expect = (max_iter + 1) * residual_comm_bytes(
        mt, ntb, kt, NB, p, q, "psum", "ozaki")
    assert got == expect
    # the mesh-reduced norm pair rides the same loop scope: one psum of
    # the stacked (2, mtl, nb) row sums per iteration
    norm_bytes = 2 * mtl * NB * 8
    norm_recs = [(nb_, m) for op, nb_, m in recs
                 if op.startswith("psum") and nb_ == norm_bytes]
    assert (norm_bytes, (max_iter + 1)) in norm_recs


# ---------------------------------------------------------------------------
# zero host syncs: the warm refinement program dispatches under a
# disallow-transfers guard (the while_loop never reads back)
# ---------------------------------------------------------------------------


def test_refinement_loop_zero_host_syncs(rng):
    from slate_tpu.parallel.dist import DistMatrix
    from slate_tpu.parallel.dist_chol import potrf_dist
    from slate_tpu.parallel.dist_refine import _astype_dist, _ir_posv_jit

    mesh = mesh24()
    a = _spd(rng)
    b = _rhs(rng)
    ad = from_dense(a, mesh, NB, diag_pad_one=True)
    a32 = _astype_dist(ad, jnp.float32)
    l, info = potrf_dist(a32)
    statics = (mesh, 2, 4, N, NRHS, NB, 30, None, "auto", "f64")
    bt = from_dense(b, mesh, NB).tiles
    out = _ir_posv_jit(ad.tiles, bt, l.tiles, info, *statics)  # warm-up
    jax.block_until_ready(out)
    bt2 = from_dense(b, mesh, NB).tiles  # fresh RHS: bt was donated
    jax.block_until_ready((ad.tiles, bt2, l.tiles, info))
    with jax.transfer_guard("disallow"):
        out2 = _ir_posv_jit(ad.tiles, bt2, l.tiles, info, *statics)
    x_t, _r, iters, conv, _rn, _xn = jax.block_until_ready(out2)
    assert bool(conv) and int(iters) >= 0


# ---------------------------------------------------------------------------
# obs: the ir section reaches RunReports and the --check gate
# ---------------------------------------------------------------------------


def test_ir_counters_reach_runreport():
    from slate_tpu import obs
    from slate_tpu.linalg.refine import ir_count
    from slate_tpu.obs import report

    obs.reset()
    ir_count("ir.solves", "gesv")
    ir_count("ir.converged", "gesv")
    ir_count("ir.iters_total", "gesv", 3)
    rep = report.make_report("mixed_test")
    assert report.validate_report(rep) == []
    assert rep["ir"]["solves"] == 1.0
    assert rep["ir"]["iters_total"] == 3.0
    vals = report.load_values(rep)
    assert vals["ir_converged"] == 1.0
    # convergence collapsing to zero under a fixed workload is a FAIL
    old = dict(vals)
    new = dict(vals, ir_converged=0.0)
    failures, _ = report.check_regression(new, old)
    assert any("ir_converged" in f for f in failures)
    # iters rising beyond threshold is a FAIL (lower-is-better)
    new2 = dict(vals, ir_iters_total=30.0)
    failures2, _ = report.check_regression(new2, old, threshold=1.5)
    assert any("ir_iters_total" in f for f in failures2)
    obs.reset()
